"""Compiled relations (ISSUE 14): hierarchy tables, numeric/set kernels,
metadata prefetch.

Pins the tentpole contracts:

  - ancestor closure math (deep chains, diamonds, cycles, unknowns)
  - numeric comparator semantics (int32 bounds, bounded-arithmetic
    constants, invalid constants erroring like invalid regexes)
  - 3-seed property: relation-table + numeric + large-set verdicts AND
    attribution are bit-identical across the matmul kernel lane, the
    gather lane, the mesh lane (2x2), the host oracle, and verdict-cache
    hits — including >= 8-level hierarchies and diamond graphs
  - ovf_assist: membership-overflow rows stay on the device lane, exactly
  - serialize round-trip, certifier mutation classes, lowerability
    (blocking_reasons rollup, metadata-prefetch caveat), rego numeric
    fragment differential, capture metadata digest, replay substitution
  - the metadata prefetch cache: detection, pinning, staleness
    fall-through, and the pipeline serving a pinned document

Deliberately import-light (collects without `cryptography`)."""

from __future__ import annotations

import asyncio
import random
import time

import jax.numpy as jnp
import numpy as np
import pytest

from authorino_tpu.analysis.fixtures import (
    fixture_relation,
    relations_fixture_configs,
    relations_fixture_policy,
)
from authorino_tpu.analysis.tensor_lint import tensor_lint
from authorino_tpu.analysis.translation_validate import (
    certify_snapshot,
    classify_entry,
    lowerability_report,
    relations_mutation_self_test,
)
from authorino_tpu.compiler.compile import (
    OP_RELATION,
    ConfigRules,
    compile_corpus,
)
from authorino_tpu.compiler.encode import encode_batch_py
from authorino_tpu.compiler.pack import batch_row_keys, pack_batch
from authorino_tpu.expressions.ast import (
    All,
    Any_,
    InGroup,
    Operator,
    Pattern,
    PatternError,
    parse_int_const,
    parse_int_value,
)
from authorino_tpu.models.policy_model import PolicyModel, host_results
from authorino_tpu.ops import pattern_eval as pe
from authorino_tpu.relations.closure import RelationClosure
from authorino_tpu.relations.prefetch import (
    MetadataPrefetcher,
    doc_digest,
    is_prefetchable,
    mark_prefetchable,
)
from authorino_tpu.runtime import EngineEntry, PolicyEngine


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# closure math
# ---------------------------------------------------------------------------


def test_closure_deep_chain_and_diamond():
    rel = fixture_relation()
    # 9-level chain: lvl0 reaches every ancestor transitively
    assert rel.contains("lvl0", "lvl9")
    assert rel.contains("lvl0", "all")
    assert rel.depth() >= 8
    # diamond: alice reaches staff through BOTH eng and ops, exactly once
    assert rel.contains("alice", "staff") and rel.contains("alice", "all")
    assert rel.groups_of("alice") >= {"eng", "ops", "staff", "all"}
    # no sideways leakage
    assert not rel.contains("alice", "qa")
    assert not rel.contains("eve", "staff")
    # unknown entities are in no groups; groups don't contain themselves
    assert rel.groups_of("nobody") == frozenset()
    assert not rel.contains("staff", "staff")


def test_closure_cycle_safe_and_digest_canonical():
    cyc = RelationClosure([("a", "b"), ("b", "c"), ("c", "a")])
    # a cycle's members converge on the cycle's union — and terminate
    assert cyc.groups_of("a") == {"a", "b", "c"}
    # digest is order/duplication independent
    r1 = RelationClosure([("x", "y"), ("y", "z")])
    r2 = RelationClosure([("y", "z"), ("x", "y"), ("x", "y")])
    assert r1.digest == r2.digest and r1 == r2


# ---------------------------------------------------------------------------
# numeric semantics
# ---------------------------------------------------------------------------


def test_numeric_parse_and_bounded_arith():
    assert parse_int_value("42") == 42
    assert parse_int_value("-7") == -7
    assert parse_int_value("4.2") is None
    assert parse_int_value("") is None
    # out-of-int32 values SATURATE (order-exact against the strictly-
    # interior constants, so the rego interpreter-equivalence proof holds
    # for arbitrarily large integers)
    assert parse_int_value(str(1 << 40)) == (1 << 31) - 1
    assert parse_int_value(str(-(1 << 40))) == -(1 << 31)
    assert parse_int_const("1024*1024") == 1 << 20
    assert parse_int_const(" 10 - 3 ") == 7
    with pytest.raises(ValueError):
        parse_int_const("1 << 4")
    with pytest.raises(ValueError):
        parse_int_const(str(1 << 31))  # int32 overflow
    with pytest.raises(ValueError):
        parse_int_const(str((1 << 31) - 1))  # endpoint excluded (open bound)


def test_numeric_pattern_invalid_const_denies_like_invalid_regex():
    bad = Pattern("a.b", Operator.GT, "not-a-number")
    with pytest.raises(PatternError):
        bad.matches({"a": {"b": 5}})
    # lowered: the whole tree rides the CPU oracle (error ⇒ deny)
    pol = compile_corpus([ConfigRules(name="c", evaluators=[(None, bad)])])
    own, _, _ = host_results(pol, {"a": {"b": 5}}, 0)
    assert own is False
    m = PolicyModel(pol)
    assert m.decide([{"a": {"b": 5}}], ["c"]) == [False]


def test_numeric_boundaries_all_ops():
    cfg = ConfigRules(name="n", evaluators=[
        (None, Pattern("v.x", Operator.GT, "10")),
        (None, Pattern("v.x", Operator.GE, "10")),
        (None, Pattern("v.x", Operator.LT, "20")),
        (None, Pattern("v.x", Operator.LE, "20")),
    ])
    m = PolicyModel.from_configs([cfg])
    for x in (9, 10, 11, 19, 20, 21, -(1 << 31), (1 << 31) - 1, 1 << 40,
              -(1 << 40), "zzz", None, 10.5):
        doc = {"v": {"x": x}}
        assert m.decide([doc], ["n"]) == \
            [host_results(m.policy, doc, 0)[0]], f"x={x!r}"
    # saturation is order-exact: a >2^31 value must still satisfy GT
    assert Pattern("v.x", Operator.GT, "10").matches({"v": {"x": 1 << 40}})
    assert not Pattern("v.x", Operator.LE, "10").matches(
        {"v": {"x": 1 << 40}})


# ---------------------------------------------------------------------------
# 3-seed cross-lane property: kernel (both lanes), mesh 2x2, host oracle,
# verdict-cache hits — verdicts AND attribution bit-identical
# ---------------------------------------------------------------------------


def _random_corpus(rng: random.Random, n_configs=6, members_k=4):
    # one deep + diamond hierarchy shared by several configs, one disjoint
    deep = [(f"d{i}", f"d{i+1}") for i in range(9)]
    rel_a = RelationClosure(
        deep + [("u1", "left"), ("u1", "right"), ("left", "mid"),
                ("right", "mid"), ("mid", "top"), ("d0", "top"),
                ("u2", "left")])
    rel_b = RelationClosure([("x", "y"), ("y", "z"), ("w", "z")])
    groups_a = ["mid", "top", "left", "d5", "d9"]
    cfgs = []
    for i in range(n_configs):
        leaves = [
            InGroup("auth.identity.sub", rng.choice(groups_a), rel_a),
            InGroup("auth.identity.team", "z", rel_b),
            Pattern("req.n", rng.choice(
                [Operator.GT, Operator.GE, Operator.LT, Operator.LE]),
                str(rng.randrange(-5, 30))),
            Pattern("auth.identity.roles", Operator.INCL, f"r{i % 3}"),
            Pattern("auth.identity.roles", Operator.EXCL, f"ban{i % 2}"),
            Pattern("req.m", Operator.EQ, rng.choice(["GET", "POST"])),
        ]
        rng.shuffle(leaves)
        rule = All(leaves[0], Any_(*leaves[1:4]))
        cond = Any_(leaves[4], leaves[5]) if rng.random() < 0.5 else None
        cfgs.append(ConfigRules(name=f"cfg-{i}",
                                evaluators=[(cond, rule), (None, leaves[1])]))
    ents = [e for e in rel_a.entities] + ["stranger"]
    docs = []
    for _ in range(64):
        docs.append({
            "req": {"n": rng.choice([-10, 0, 3, 7, 29, 30, "x", None]),
                    "m": rng.choice(["GET", "POST", "PUT"])},
            "auth": {"identity": {
                "sub": rng.choice(ents),
                "team": rng.choice(["x", "y", "w", "z", "q"]),
                "roles": [f"r{rng.randrange(4)}"
                          for _ in range(rng.choice([1, 2, members_k + 2]))],
            }},
        })
    names = [f"cfg-{rng.randrange(n_configs)}" for _ in docs]
    return cfgs, docs, names


def _kernel_full(policy, docs, rows, lane):
    params = pe.to_device(policy, lane=lane)
    enc = encode_batch_py(policy, docs, rows)
    db = pack_batch(policy, enc)
    has_dfa = params["dfa_tables"] is not None
    own, own_rule, own_skip = pe.eval_full_jit(
        params, jnp.asarray(db.attrs_val), jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense), jnp.asarray(db.config_id),
        jnp.asarray(db.attr_bytes) if has_dfa else None,
        jnp.asarray(db.byte_ovf) if has_dfa else None,
        *pe._extra_operands(db))
    return (np.asarray(own), np.asarray(own_rule), np.asarray(own_skip),
            db.host_fallback)


@pytest.mark.parametrize("seed", [3, 17, 29])
def test_relation_lanes_bit_identical_property(seed):
    rng = random.Random(seed)
    cfgs, docs, names = _random_corpus(rng)
    policy = compile_corpus(cfgs, members_k=4, ovf_assist=True)
    assert not tensor_lint(policy)
    rows = [policy.config_ids[n] for n in names]
    want = [host_results(policy, d, r) for d, r in zip(docs, rows)]
    w_fire = pe.firing_columns(np.stack([w[1] for w in want]),
                               np.stack([w[2] for w in want]))
    for lane in ("matmul", "gather"):
        own, own_rule, own_skip, fb = _kernel_full(policy, docs, rows, lane)
        assert not fb.any()  # ovf_assist: no lossy rows
        n = len(docs)
        fire = pe.firing_columns(own_rule[:n], own_skip[:n])
        for i in range(n):
            assert bool(own[i]) == want[i][0], (lane, i)
            assert int(fire[i]) == int(w_fire[i]), (lane, i)
    # the compiled artifact certifies against the host oracle too
    _, fails, _ = certify_snapshot(policy, use_cache=False)
    assert not fails, fails[:3]


@pytest.mark.mesh
@pytest.mark.parametrize("seed", [5, 23, 41])
def test_relation_mesh_2x2_parity(seed, mesh_devices):
    from authorino_tpu.parallel import ShardedPolicyModel, build_mesh

    rng = random.Random(seed)
    cfgs, docs, names = _random_corpus(rng)
    mesh = build_mesh(n_devices=4, dp=2)  # 2x2
    sharded = ShardedPolicyModel(cfgs, mesh, members_k=4, ovf_assist=True)
    own_rule, own_skip = sharded.run_full(docs, names)
    n = len(docs)
    fire = pe.firing_columns(own_rule[:n], own_skip[:n])
    for i, (d, name) in enumerate(zip(docs, names)):
        shard, row = sharded.locator[name]
        w_own, w_rule, w_skip = host_results(sharded.shards[shard], d, row)
        w_fire = pe.firing_columns(w_rule[None, :], w_skip[None, :])[0]
        got_own = bool(np.all(own_skip[i] | own_rule[i]))
        assert got_own == w_own, i
        assert int(fire[i]) == int(w_fire), i


def test_relation_verdict_cache_hits_identical():
    """The same relation/numeric rows through a cache-enabled engine twice:
    the second (cache-hit) pass resolves bit-identically and actually
    hits."""
    rel = fixture_relation()
    rule = All(InGroup("auth.identity.sub", "staff", rel),
               Pattern("request.size", Operator.LE, "1024"))
    engine = PolicyEngine(members_k=4, mesh=None, max_batch=8,
                          lane_select=False, verdict_cache_size=1024,
                          metadata_prefetch=False)
    engine.apply_snapshot([EngineEntry(
        id="c", hosts=["c"], runtime=None,
        rules=ConfigRules(name="c", evaluators=[(None, rule)]))])
    policy = engine._snapshot.policy
    docs = [{"auth": {"identity": {"sub": s}},
             "request": {"size": z}}
            for s, z in (("alice", 10), ("eve", 10), ("alice", 4096),
                         ("lvl0", 0), ("nobody", 1))]

    async def burst():
        return await asyncio.gather(*(engine.submit(d, "c") for d in docs))

    first = run(burst())
    hits0 = engine._verdict_cache.hits
    second = run(burst())
    assert engine._verdict_cache.hits > hits0
    for (r1, s1), (r2, s2), d in zip(first, second, docs):
        np.testing.assert_array_equal(r1, r2)
        np.testing.assert_array_equal(s1, s2)
        w_own, w_rule, w_skip = host_results(policy, d, 0)
        np.testing.assert_array_equal(r1, w_rule)
        np.testing.assert_array_equal(s1, w_skip)


# ---------------------------------------------------------------------------
# ovf_assist
# ---------------------------------------------------------------------------


def test_ovf_assist_exact_and_no_fallback():
    cfgs = [ConfigRules(name="m", evaluators=[(None, All(
        Pattern("auth.identity.roles", Operator.INCL, "admin"),
        Pattern("auth.identity.groups", Operator.EXCL, "banned")))])]
    K = 4
    docs = [
        {"auth": {"identity": {"roles": [f"r{i}" for i in range(9)]
                               + ["admin"], "groups": ["x"]}}},
        {"auth": {"identity": {"roles": [f"r{i}" for i in range(9)],
                               "groups": ["x"]}}},
        {"auth": {"identity": {"roles": ["admin"],
                               "groups": ["banned"] * 9}}},
        {"auth": {"identity": {"roles": ["admin"], "groups": ["ok"] * 9}}},
        {"auth": {"identity": {"roles": ["admin"], "groups": ["x"]}}},
    ]
    rows = [0] * len(docs)
    assisted = compile_corpus(cfgs, members_k=K, ovf_assist=True)
    legacy = compile_corpus(cfgs, members_k=K, ovf_assist=False)
    db_l = pack_batch(legacy, encode_batch_py(legacy, docs, rows))
    assert db_l.host_fallback[:4].all() and not db_l.host_fallback[4]
    for lane in ("matmul", "gather"):
        own, _, _, fb = _kernel_full(assisted, docs, rows, lane)
        assert not fb.any()
        assert [bool(b) for b in own[:len(docs)]] == \
            [host_results(assisted, d, 0)[0] for d in docs]
    # overflow state rides the row keys: same visible prefix, different
    # overflow answers must never alias
    db = pack_batch(assisted, encode_batch_py(assisted, docs, rows))
    assert db.member_ovf is not None and db.member_ovf.any()
    assert len(set(batch_row_keys(db, len(docs)))) == len(docs)


def test_ovf_assist_drops_grid_overflow_reason():
    from types import SimpleNamespace

    cfgs = [ConfigRules(name="m", evaluators=[(None, Pattern(
        "auth.identity.roles", Operator.INCL, "admin"))])]
    entry = SimpleNamespace(id="m", rules=cfgs[0], runtime=None)
    lane_a, reasons_a = classify_entry(
        entry, policy=compile_corpus(cfgs, members_k=4, ovf_assist=True))
    lane_l, reasons_l = classify_entry(
        entry, policy=compile_corpus(cfgs, members_k=4, ovf_assist=False))
    assert lane_a == lane_l == "fast"
    assert "cpu-grid-overflow" in reasons_l
    assert "cpu-grid-overflow" not in reasons_a


# ---------------------------------------------------------------------------
# serialize + certifier + lowerability satellites
# ---------------------------------------------------------------------------


def test_relation_serialize_roundtrip_and_certify():
    from authorino_tpu.snapshots.serialize import (
        deserialize_policy,
        serialize_policy,
    )

    pol = relations_fixture_policy()
    loaded, _ = deserialize_policy(serialize_policy(pol))
    for name in ("rel_bits", "leaf_rel_slot", "leaf_rel_col",
                 "num_attr_slot", "leaf_op", "leaf_const"):
        np.testing.assert_array_equal(getattr(pol, name),
                                      getattr(loaded, name))
    assert loaded.ovf_assist and loaded.n_rel_slots == pol.n_rel_slots
    assert [c.digest for c in loaded.rel_instances] == \
        [c.digest for c in pol.rel_instances]
    _, fails, _ = certify_snapshot(loaded, use_cache=False)
    assert not fails, fails[:3]
    # old-format blobs (no new lanes) still carry version 1
    plain = compile_corpus([ConfigRules(name="p", evaluators=[
        (None, Pattern("a.b", Operator.EQ, "x"))])])
    import json as _json
    import struct

    blob = serialize_policy(plain)
    hlen = struct.unpack_from("<Q", blob, 10)[0]
    assert _json.loads(blob[18:18 + hlen])["version"] == 1
    blob2 = serialize_policy(pol)
    hlen2 = struct.unpack_from("<Q", blob2, 10)[0]
    assert _json.loads(blob2[18:18 + hlen2])["version"] == 2


def test_relations_mutation_self_test_green():
    """Tier-1 gate: every ISSUE 14 miscompile class (hierarchy-closure bit
    flips, column redirects, numeric const/op/slot corruption) must be
    rejected by the certifier — a blind validator fails here."""
    assert relations_mutation_self_test() == []


def test_planted_relation_bit_flip_is_rejected():
    from copy import deepcopy

    pol = relations_fixture_policy()
    mut = deepcopy(pol)
    leaf = next(i for i in range(mut.n_leaves)
                if int(mut.leaf_op[i]) == OP_RELATION)
    col = int(mut.leaf_rel_col[leaf])
    inst, _ = mut.rel_col_names[col]
    row = next(iter(mut.rel_entity_rows[inst].values()))
    mut.rel_bits = mut.rel_bits.copy()
    mut.rel_bits[row, col >> 3] ^= np.uint8(1 << (col & 7))
    _, fails, _ = certify_snapshot(mut, use_cache=False)
    assert any(f.kind == "relation-mismatch" for f in fails)


def test_shared_column_slot_corruption_rejected_per_leaf():
    """Two leaves sharing one (closure, group) column on DIFFERENT
    selectors: corrupting the SECOND leaf's slot binding must be caught
    even though the first leaf already audited (and memoized) the
    column's bits."""
    from copy import deepcopy

    rel = RelationClosure([("alice", "staff"), ("bob", "staff")])
    pol = compile_corpus([
        ConfigRules(name="a", evaluators=[
            (None, InGroup("auth.identity.sub", "staff", rel))]),
        ConfigRules(name="b", evaluators=[
            (None, All(InGroup("context.user", "staff", rel),
                       InGroup("auth.identity.sub", "staff", rel)))]),
    ])
    _, fails, _ = certify_snapshot(pol, use_cache=False)
    assert not fails
    # both selectors query the same column through different slots
    rel_leaves = [i for i in range(pol.n_leaves)
                  if int(pol.leaf_op[i]) == OP_RELATION]
    assert len(rel_leaves) == 2
    assert int(pol.leaf_rel_col[rel_leaves[0]]) == \
        int(pol.leaf_rel_col[rel_leaves[1]])
    assert int(pol.leaf_rel_slot[rel_leaves[0]]) != \
        int(pol.leaf_rel_slot[rel_leaves[1]])
    mut = deepcopy(pol)
    mut.leaf_rel_slot = mut.leaf_rel_slot.copy()
    # rebind the SECOND leaf to the first leaf's slot (wrong attribute)
    mut.leaf_rel_slot[rel_leaves[1]] = int(pol.leaf_rel_slot[rel_leaves[0]])
    _, fails, _ = certify_snapshot(mut, use_cache=False)
    assert any(f.kind == "relation-mismatch" and "slot" in f.message
               for f in fails), fails


def test_blocking_reasons_rollup():
    from types import SimpleNamespace

    entries = [
        SimpleNamespace(id="a", rules=None, runtime=None),  # no rules only
        SimpleNamespace(id="b", rules=None, runtime=SimpleNamespace(
            metadata=[SimpleNamespace(type="METADATA_GENERIC_HTTP")],
            authorization=[SimpleNamespace(
                type="OPA",
                evaluator=SimpleNamespace(kernel_slot=None))])),
        SimpleNamespace(id="c", rules=ConfigRules(
            name="c", evaluators=[(None, Pattern(
                "request.method", Operator.EQ, "GET"))]), runtime=None),
    ]
    rep = lowerability_report(
        entries, compile_corpus([entries[2].rules]))
    b = rep["blocking_reasons"]
    # config b carries TWO reasons: neither is a sole blocker
    assert b["metadata-dependency"] == {"configs": 1, "sole_blocker": 0}
    assert b["unsupported-comparator"] == {"configs": 1, "sole_blocker": 0}
    assert b["no-authorization-rules"]["sole_blocker"] == 1
    assert rep["fast"] == 1 and rep["slow"] == 2


# ---------------------------------------------------------------------------
# metadata prefetch
# ---------------------------------------------------------------------------


class _FakeGenericHttp:
    """GenericHttp-shaped duck (is_prefetchable is duck-typed by design so
    the analysis layer stays import-light — the real GenericHttp lives
    behind the cryptography-gated evaluators.metadata package).  call()
    counts live fetches so tests can prove the pin bypassed it."""

    def __init__(self, endpoint, body=None, parameters=(), headers=()):
        from authorino_tpu.authjson.value import JSONValue

        self.endpoint = (endpoint if not isinstance(endpoint, str)
                         else JSONValue(static=endpoint))
        self.body = body
        self.parameters = list(parameters)
        self.headers = list(headers)
        self.calls = 0

    async def call(self, pipeline):
        self.calls += 1
        return {"live": True}


def _static_md_conf(name="flags", conditions=None, cache=None,
                    endpoint="http://md.internal/flags"):
    from authorino_tpu.evaluators.base import MetadataConfig

    return MetadataConfig(name, _FakeGenericHttp(endpoint),
                          type="METADATA_GENERIC_HTTP",
                          conditions=conditions, cache=cache)


def test_prefetchable_detection():
    from authorino_tpu.authjson.value import JSONValue
    from authorino_tpu.evaluators.base import MetadataConfig

    assert is_prefetchable(_static_md_conf())
    # templated endpoint → request-dependent
    ev = _FakeGenericHttp(JSONValue(pattern="http://x/{request.path}"))
    assert not is_prefetchable(MetadataConfig(
        "t", ev, type="METADATA_GENERIC_HTTP"))
    # selector-valued header → request-dependent
    from types import SimpleNamespace

    ev2 = _FakeGenericHttp("http://x", headers=[SimpleNamespace(
        name="h", value=JSONValue(pattern="auth.identity.sub"))])
    assert not is_prefetchable(MetadataConfig(
        "t2", ev2, type="METADATA_GENERIC_HTTP"))
    # conditions gate → request-dependent
    assert not is_prefetchable(_static_md_conf(
        conditions=Pattern("request.method", Operator.EQ, "GET")))
    # non-GenericHttp types never prefetch
    assert not is_prefetchable(MetadataConfig(
        "u", object(), type="METADATA_USERINFO"))
    conf = _static_md_conf()
    assert mark_prefetchable(conf) and conf.prefetchable
    assert conf.prefetch_pinned is False


def test_prefetcher_pins_and_pipeline_serves_without_fetch():
    conf = _static_md_conf()
    mark_prefetchable(conf)
    entry = EngineEntry(id="ns/c", hosts=["c"], runtime=None, rules=None)
    entry.runtime = type("RT", (), {"metadata": [conf]})()
    fetches = []

    def fake_fetch(evaluator):
        fetches.append(evaluator)
        return {"tier": "gold"}

    pf = MetadataPrefetcher(max_age_s=60.0, refresh_s=3600.0,
                            fetcher=fake_fetch)
    try:
        assert pf.reconcile([entry]) == 1
        assert conf.prefetch_pinned is True
        pf.refresh()
        rec = pf.lookup(("ns/c", "flags"))
        assert rec is not None and rec.doc == {"tier": "gold"}
        assert rec.digest == doc_digest({"tier": "gold"})
        # the pipeline's metadata call serves the PIN — the evaluator's
        # live call (which would hit the network) never runs
        got = run(conf.call(object()))
        assert got == {"tier": "gold"}
        assert conf.evaluator.calls == 0
        assert pf.digest_for("ns/c") is not None
        assert pf.export_docs() == {"ns/c": {"flags": {"tier": "gold"}}}
    finally:
        pf.stop()


def test_prefetcher_transient_failure_keeps_healthy_pin():
    """A failed re-pin must NOT evict a still-fresh healthy pin: the
    previous document keeps serving (with its original fetched_at) until
    the staleness bound — the contract the error metric documents."""
    conf = _static_md_conf()
    mark_prefetchable(conf)
    entry = EngineEntry(id="ns/c", hosts=["c"], runtime=None, rules=None)
    entry.runtime = type("RT", (), {"metadata": [conf]})()
    state = {"fail": False}

    def flaky(ev):
        if state["fail"]:
            raise RuntimeError("metadata service down")
        return {"tier": "gold"}

    pf = MetadataPrefetcher(max_age_s=60.0, refresh_s=3600.0, fetcher=flaky)
    try:
        pf.reconcile([entry])
        pf.refresh()
        assert pf.lookup(("ns/c", "flags")).doc == {"tier": "gold"}
        state["fail"] = True
        pf.refresh()  # transient failure
        rec = pf.lookup(("ns/c", "flags"))
        assert rec is not None and rec.doc == {"tier": "gold"}
        assert pf.to_json()["counters"]["error"] >= 1
    finally:
        pf.stop()


def test_prefetcher_staleness_falls_through():
    conf = _static_md_conf()
    mark_prefetchable(conf)
    entry = EngineEntry(id="ns/c", hosts=["c"], runtime=None, rules=None)
    entry.runtime = type("RT", (), {"metadata": [conf]})()
    pf = MetadataPrefetcher(max_age_s=0.0, refresh_s=3600.0,
                            fetcher=lambda ev: {"x": 1})
    try:
        pf.reconcile([entry])
        pf.refresh()
        time.sleep(0.01)
        assert pf.lookup(("ns/c", "flags")) is None  # stale → fall-through
        assert pf.to_json()["counters"]["stale"] >= 1
    finally:
        pf.stop()


def test_classify_entry_metadata_prefetch_caveat():
    from types import SimpleNamespace

    rules = ConfigRules(name="c", evaluators=[
        (None, Pattern("request.method", Operator.EQ, "GET"))])
    pol = compile_corpus([rules])

    def entry(pinned):
        return SimpleNamespace(id="c", rules=rules, runtime=SimpleNamespace(
            metadata=[SimpleNamespace(type="METADATA_GENERIC_HTTP",
                                      prefetchable=pinned,
                                      prefetch_pinned=pinned)],
            authorization=[SimpleNamespace(type="PATTERN_MATCHING",
                                           evaluator=SimpleNamespace())]))

    lane, reasons = classify_entry(entry(False), policy=pol)
    assert lane == "slow" and "metadata-dependency" in reasons
    lane, reasons = classify_entry(entry(True), policy=pol)
    assert lane == "fast" and "metadata-prefetch" in reasons


def test_engine_reconcile_registers_prefetch_and_reports_fast():
    conf = _static_md_conf()
    mark_prefetchable(conf)
    rules = ConfigRules(name="ns/c", evaluators=[
        (None, Pattern("request.method", Operator.EQ, "GET"))])
    runtime = type("RT", (), {"metadata": [conf], "authorization": []})()
    engine = PolicyEngine(members_k=4, mesh=None, lane_select=False,
                          metadata_prefetch=True)
    engine.metadata_prefetcher._fetcher = lambda ev: {"ok": True}
    try:
        engine.apply_snapshot([EngineEntry(id="ns/c", hosts=["c"],
                                           runtime=runtime, rules=rules)])
        assert conf.prefetch_pinned is True
        rep = engine._lowerability
        assert rep["configs"]["ns/c"]["lane"] == "fast"
        assert "metadata-prefetch" in rep["configs"]["ns/c"]["reasons"]
        dv = engine.debug_vars()
        assert dv["metadata_prefetch"]["registered"] == 1
    finally:
        engine.metadata_prefetcher.stop()


# ---------------------------------------------------------------------------
# rego numeric fragment differential
# ---------------------------------------------------------------------------


def test_rego_numeric_fragment_differential():
    from authorino_tpu.evaluators.authorization import rego
    from authorino_tpu.evaluators.authorization.rego_lower import (
        lower_verdict,
    )

    src = ("default allow = false\n"
           "allow { input.request.size > 1024 }\n"
           "allow { input.source.port >= 8000; input.source.port <= 8080 }\n"
           "allow { 4096 > input.request.size; "
           'input.request.method == "GET" }\n'
           "allow { input.request.size == 0 }\n")
    mod = rego.compile_module(src, package="t")
    lowered = lower_verdict(mod)
    assert lowered is not None
    rng = random.Random(9)
    for _ in range(300):
        doc = {"request": {"size": rng.choice(
            [-1, 0, 1, 1024, 1025, 4095, 4096, 10_000_000]),
            "method": rng.choice(["GET", "POST"])}}
        if rng.random() < 0.5:
            doc["source"] = {"port": rng.choice([7999, 8000, 8080, 8081])}
        want = bool(mod.evaluate(doc).get("allow"))
        assert lowered.matches(doc) == want, doc


# ---------------------------------------------------------------------------
# translate: relations spec + ingroup operator
# ---------------------------------------------------------------------------


def test_translate_relations_spec_and_ingroup():
    # the translate layer imports the full evaluator tree (cryptography-
    # gated on this image, like every translate suite)
    pytest.importorskip("cryptography")
    from authorino_tpu.controllers.translate import translate_auth_config

    spec = {
        "hosts": ["svc.example.com"],
        "relations": {"org": {"edges": [
            ["alice", "eng"], ["eng", "staff"], ["staff", "all"]]}},
        "authentication": {"anon": {"anonymous": {}}},
        "authorization": {"hier": {"patternMatching": {"patterns": [
            {"selector": "auth.identity.sub", "operator": "ingroup",
             "value": "staff", "relation": "org"},
            {"selector": "request.size", "operator": "le",
             "value": "1024*1024"},
        ]}}},
    }
    entry = run(translate_auth_config("c", "ns", spec))
    assert entry.rules is not None
    (cond, rule), = entry.rules.evaluators
    assert rule.matches({"auth": {"identity": {"sub": "alice"}},
                         "request": {"size": 10}})
    assert not rule.matches({"auth": {"identity": {"sub": "eve"}},
                             "request": {"size": 10}})
    assert not rule.matches({"auth": {"identity": {"sub": "alice"}},
                             "request": {"size": 1 << 21}})
    # unknown relation name is a TranslationError
    from authorino_tpu.controllers.translate import TranslationError

    bad = dict(spec)
    bad["authorization"] = {"h": {"patternMatching": {"patterns": [
        {"selector": "s", "operator": "ingroup", "value": "g",
         "relation": "nope"}]}}}
    with pytest.raises(TranslationError):
        run(translate_auth_config("c", "ns", bad))


# ---------------------------------------------------------------------------
# capture digest + replay substitution
# ---------------------------------------------------------------------------


def test_capture_record_carries_metadata_digest():
    from authorino_tpu.replay.capture import CAPTURE_FIELDS, CaptureLog

    cap = CaptureLog(enabled=True, size_mb=1.0)
    cap.offer("ns/c", {"request": {"path": "/x"}}, -1, "engine", 3,
              metadata_doc_digest="abc123")
    cap.offer("ns/d", {"request": {"path": "/y"}}, 0, "engine", 3)
    cap.flush()
    recs = cap.ring_records()
    assert len(recs) == 2
    by_cfg = {r["authconfig"]: r for r in recs}
    assert by_cfg["ns/c"]["metadata_doc_digest"] == "abc123"
    assert by_cfg["ns/d"]["metadata_doc_digest"] is None
    for r in recs:
        assert tuple(sorted(r)) == tuple(sorted(CAPTURE_FIELDS))


def test_replay_metadata_substitution_unblinds():
    from authorino_tpu.replay.replay import replay_records

    rule = Pattern("auth.metadata.flags.tier", Operator.EQ, "gold")
    pol = compile_corpus([ConfigRules(name="c", evaluators=[(None, rule)])])
    captured_doc = {"request": {"method": "GET", "path": "/x"},
                    "auth": {"metadata": {"flags": {"tier": "bronze"}}}}
    records = [{"schema": 2, "authconfig": "c", "doc": captured_doc,
                "verdict": "deny", "rule_index": 0, "lane": "engine",
                "generation": 1, "metadata_doc_digest": "stale-digest"}]
    # blind replay: captured (bronze) document → denied on both sides
    blind = replay_records(pol, pol, records)
    assert blind["metadata"]["substituted"] == 0
    # pinned document says tier=gold → the what-if re-decides under it
    docs = {"c": {"flags": {"tier": "gold"}}}
    seen = replay_records(pol, pol, records, metadata_docs=docs)
    assert seen["metadata"]["substituted"] == 1
    assert seen["metadata"]["digest_mismatches"] == 1
    assert seen["per_config"]["c"]["new_allows"] == 1
    # the caller's record is untouched (shallow-copy substitution)
    assert captured_doc["auth"]["metadata"]["flags"]["tier"] == "bronze"


# ---------------------------------------------------------------------------
# epoch/fingerprint sensitivity
# ---------------------------------------------------------------------------


def test_edge_change_refingerprints_relation_configs():
    from authorino_tpu.snapshots.fingerprint import (
        encoding_epoch,
        rules_fingerprint,
    )

    rel1 = RelationClosure([("a", "g"), ("g", "top")])
    rel2 = RelationClosure([("a", "g"), ("g", "top"), ("b", "g")])

    def cfg(rel):
        return ConfigRules(name="c", evaluators=[
            (None, InGroup("auth.identity.sub", "top", rel))])

    assert rules_fingerprint(cfg(rel1)) != rules_fingerprint(cfg(rel2))
    assert rules_fingerprint(cfg(rel1)) == rules_fingerprint(cfg(rel1))
    p1 = compile_corpus([cfg(rel1)])
    p2 = compile_corpus([cfg(rel2)])
    p1b = compile_corpus([cfg(rel1)])
    assert encoding_epoch(p1) != encoding_epoch(p2)
    # same interner object → identical epoch for identical layout
    p1c = compile_corpus([cfg(rel1)], interner=p1.interner)
    assert encoding_epoch(p1) == encoding_epoch(p1c)
    assert p1b is not p1
