"""Device regex lane: DFA compiler exactness vs `re`, kernel integration,
overflow fallback, and end-to-end agreement with the CPU oracle on
regex-heavy corpora."""

import random
import re as re_mod

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus, encode_batch
from authorino_tpu.compiler.compile import OP_CPU, OP_REGEX_DFA
from authorino_tpu.compiler.redfa import compile_regex_dfa
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.compiler.pack import pack_batch
from authorino_tpu.ops import eval_batch_jit, to_device

from test_compiler_differential import oracle_verdict

PATTERNS = [
    r"^/pets/\d+$", r"\d+", r"^(GET|POST)$", r"adm.n", r"^$", r"abc",
    r"^/api/v\d+/r\d", r"[a-f0-9]{4}", r"a+b*c?", r"(foo|bar)+baz",
    r"^x[^y]z$", r"^\w+@\w+\.\w+$", r"a{2,4}", r"^-?\d+(\.\d+)?$",
    r"(?:ab|cd)ef", r"^Bearer ", r"\.json$", r"^[A-Z][a-z]+$",
]

STRINGS = ["", "/pets/1", "/pets/123x", "GET", "POST", "PUT", "admin", "admon",
           "abc", "xabcx", "/api/v2/r3", "deadbeef", "aabbc", "foobarbaz",
           "xaz", "a@b.co", "aa", "aaaaa", "42", "-3.14", "abef", "cdef",
           "Bearer tok", "data.json", "Hello", "hello", "x" * 200]


def dfa_match(dfa, s: str):
    bs = s.encode("utf-8")
    st = dfa.start
    for b in bs:
        st = int(dfa.trans[st, b])
    return bool(dfa.accept[st])


def test_dfa_compiler_exact_vs_re():
    for p in PATTERNS:
        dfa = compile_regex_dfa(p)
        assert dfa is not None, f"pattern unexpectedly unsupported: {p}"
        gold = re_mod.compile(p)
        for s in STRINGS:
            assert dfa_match(dfa, s) == (gold.search(s) is not None), (p, s)


def test_unsupported_patterns_fall_back():
    # backreferences / lookaheads are not RE2 (the reference rejects them
    # too); unicode classes and huge repeats exceed the device subset
    assert compile_regex_dfa(r"x{100}") is None
    assert compile_regex_dfa(r"(?=foo)") is None


def test_kernel_uses_dfa_lane():
    configs = [
        ConfigRules("c", evaluators=[(None, Pattern("path", Operator.MATCHES, r"^/pets/\d+$"))]),
    ]
    policy = compile_corpus(configs)
    assert (policy.leaf_op == OP_REGEX_DFA).any()
    assert policy.n_byte_attrs == 1
    params = to_device(policy)
    docs = [{"path": "/pets/1"}, {"path": "/pets/x"}, {"path": "/pets/123"}, {"path": ""}]
    enc = encode_batch(policy, docs, [0] * 4)
    # the CPU lane must NOT have been consulted for in-range values
    assert not enc.cpu_lane.any()
    own, _ = eval_batch_jit(params, pack_batch(policy, enc))
    assert list(own) == [True, False, True, False]


def test_long_value_overflow_falls_back_to_cpu():
    configs = [
        ConfigRules("c", evaluators=[(None, Pattern("v", Operator.MATCHES, r"needle$"))]),
    ]
    policy = compile_corpus(configs)
    long_hit = "x" * 300 + "needle"        # > DFA_VALUE_BYTES
    long_miss = "x" * 300
    nul_hit = "a\x00needle"                # NUL byte → CPU lane
    docs = [{"v": long_hit}, {"v": long_miss}, {"v": nul_hit}, {"v": "short needle"}]
    enc = encode_batch(policy, docs, [0] * 4)
    assert enc.byte_ovf[:3, 0].all() and not enc.byte_ovf[3, 0]
    own, _ = eval_batch_jit(to_device(policy), pack_batch(policy, enc))
    assert list(own) == [True, False, True, True]


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_regex_heavy_corpus_matches_oracle(seed):
    rng = random.Random(seed)
    configs = []
    for i in range(8):
        pats = [
            Pattern("path", Operator.MATCHES, rng.choice(PATTERNS)),
            Pattern("name", Operator.MATCHES, rng.choice(PATTERNS)),
            Pattern("tag", Operator.EQ, rng.choice(["a", "b"])),
        ]
        comb = All if rng.random() < 0.5 else Any_
        configs.append(ConfigRules(f"cfg-{i}", evaluators=[(None, comb(*pats))]))
    policy = compile_corpus(configs)
    params = to_device(policy)
    docs = [
        {"path": rng.choice(STRINGS), "name": rng.choice(STRINGS), "tag": rng.choice(["a", "b", "c"])}
        for _ in range(48)
    ]
    rows = [rng.randrange(len(configs)) for _ in docs]
    enc = encode_batch(policy, docs, rows)
    own, _ = eval_batch_jit(params, pack_batch(policy, enc))
    for r, (doc, row) in enumerate(zip(docs, rows)):
        assert bool(own[r]) == oracle_verdict(configs[row], doc), (seed, r, doc)


def test_determinization_memo_keys_distinguish_anchoring():
    """Audit of the process-wide determinization memo (compiler/redfa.py
    _DFA_MEMO): the key is the FULL pattern string, and anchoring lives in
    the pattern string itself (``^``/``$`` prefixes/suffixes), so variants
    of one body can never share an entry.  There is no flags parameter in
    the API at all — nothing else can alias.  Regression-pins both the
    isolation (distinct languages per variant) and the memo behaviour
    (same pattern → the SAME immutable DFA object, cross-snapshot)."""
    variants = ["abc", "^abc", "abc$", "^abc$"]
    dfas = {p: compile_regex_dfa(p) for p in variants}
    assert all(d is not None for d in dfas.values())
    # each anchoring variant decides a different language on these probes
    probes = ["abc", "xabc", "abcx", "xabcx", ""]
    behaviours = {p: tuple(dfa_match(d, s) for s in probes)
                  for p, d in dfas.items()}
    assert len(set(behaviours.values())) == len(variants), behaviours
    assert behaviours["abc"] == (True, True, True, True, False)
    assert behaviours["^abc"] == (True, False, True, False, False)
    assert behaviours["abc$"] == (True, True, False, False, False)
    assert behaviours["^abc$"] == (True, False, False, False, False)
    # memo hit: byte-identical pattern returns the identical object (what
    # lets the compiler's table dedup collapse repeats across snapshots)
    for p in variants:
        assert compile_regex_dfa(p) is dfas[p]
    # ...and an escaped trailing dollar is NOT treated as an end anchor
    esc = compile_regex_dfa(r"abc\$")
    assert esc is not None and esc is not dfas["abc$"]
    assert dfa_match(esc, "abc$x") and not dfa_match(esc, "abc")
