"""TLS on the serving listeners: the ext_authz gRPC frontend must accept
TLS >= 1.2 connections and the HTTP adapter must serve HTTPS when
--tls-cert/--tls-cert-key are given (ref: main.go:456-470)."""

import asyncio
import datetime
import ssl

import grpc
import pytest

from authorino_tpu import protos
from authorino_tpu.compiler import ConfigRules
from authorino_tpu.evaluators import AuthorizationConfig, IdentityConfig, RuntimeAuthConfig
from authorino_tpu.evaluators.authorization import PatternMatching
from authorino_tpu.evaluators.identity import Noop
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.service.grpc_server import build_server

external_auth_pb2 = protos.external_auth_pb2


@pytest.fixture(scope="module")
def self_signed():
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(hours=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost")]), critical=False
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


def make_engine():
    engine = PolicyEngine(max_batch=4)
    rules = All(Pattern("request.method", Operator.NEQ, "DELETE"))
    runtime = RuntimeAuthConfig(
        identity=[IdentityConfig("anon", Noop())],
        authorization=[AuthorizationConfig("rules", PatternMatching(rules))],
    )
    engine.apply_snapshot([
        EngineEntry(id="ns/cfg", hosts=["svc.example.com"], runtime=runtime,
                    rules=ConfigRules(name="ns/cfg", evaluators=[(None, rules)]))
    ])
    return engine


def test_grpc_tls_check(self_signed):
    cert_pem, key_pem = self_signed

    async def run():
        engine = make_engine()
        creds = grpc.ssl_server_credentials([(key_pem, cert_pem)])
        # port 0: OS-assigned, like the other service tests (no EADDRINUSE)
        server = build_server(engine, address="localhost:0", tls_credentials=creds)
        port = server.bound_port
        await server.start()
        try:
            chan_creds = grpc.ssl_channel_credentials(root_certificates=cert_pem)
            async with grpc.aio.secure_channel(f"localhost:{port}", chan_creds) as ch:
                call = ch.unary_unary(
                    "/envoy.service.auth.v3.Authorization/Check",
                    request_serializer=external_auth_pb2.CheckRequest.SerializeToString,
                    response_deserializer=external_auth_pb2.CheckResponse.FromString,
                )
                req = external_auth_pb2.CheckRequest()
                http = req.attributes.request.http
                http.method = "GET"
                http.host = "svc.example.com"
                http.headers["host"] = "svc.example.com"
                resp = await call(req)
                assert resp.status.code == 0
        finally:
            await server.stop(0.1)

    asyncio.new_event_loop().run_until_complete(run())


def test_http_tls_check(self_signed, tmp_path):
    cert_pem, key_pem = self_signed
    cert_file = tmp_path / "tls.crt"
    key_file = tmp_path / "tls.key"
    cert_file.write_bytes(cert_pem)
    key_file.write_bytes(key_pem)

    from authorino_tpu.cli import _ssl_ctx
    from authorino_tpu.service.http_server import build_app

    async def run():
        import aiohttp
        from aiohttp import web

        engine = make_engine()
        server_ctx = _ssl_ctx(str(cert_file), str(key_file))
        assert server_ctx.minimum_version == ssl.TLSVersion.TLSv1_2
        runner = web.AppRunner(build_app(engine))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0, ssl_context=server_ctx)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        try:
            client_ctx = ssl.create_default_context(cadata=cert_pem.decode())
            client_ctx.check_hostname = False
            async with aiohttp.ClientSession() as sess:
                async with sess.get(
                    f"https://127.0.0.1:{port}/check",
                    headers={"Host": "svc.example.com"},
                    ssl=client_ctx,
                ) as r:
                    assert r.status == 200
        finally:
            await runner.cleanup()

    asyncio.new_event_loop().run_until_complete(run())


def test_mismatched_flags_rejected():
    from authorino_tpu.cli import _ssl_ctx

    with pytest.raises(SystemExit):
        _ssl_ctx("/some/cert.pem", "")
