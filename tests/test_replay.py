"""Traffic replay & what-if preflight (ISSUE 13, docs/replay.md).

Covers the acceptance list: the capture ring's bounded-memory property
(byte cap honored under sustained append, drops counted), capture
round-trip bit-parity through the checksummed container (+ typed
rejection of corruption/version-skew/schema-skew), replay verdict-diff
correctness on a planted one-rule mutation (exactly the mutated rule
attributed; clean churn diffs empty), pregate rejection leaving the old
snapshot serving (zero live exposure), the engine capture hook, the
decision-record schema satellites, the bench replay timetable, and the
/debug/replay endpoint.

Deliberately import-light: collects on images without ``cryptography``;
JAX_PLATFORMS=cpu."""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import struct
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.expressions import Operator, Pattern
from authorino_tpu.replay import capture as cap_mod
from authorino_tpu.replay.capture import (
    CAPTURE,
    CAPTURE_SCHEMA,
    CaptureFormatError,
    CaptureLog,
    read_capture,
    read_segment,
    write_segment,
)
from authorino_tpu.replay.pregate import pregate_check
from authorino_tpu.replay.replay import replay_records
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime import provenance as prov_mod
from authorino_tpu.runtime.change_safety import GuardThresholds
from authorino_tpu.runtime.engine import SnapshotRejected
from authorino_tpu.runtime.flight_recorder import ANOMALY_KINDS, RECORDER


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def org_corpus(orgs):
    return [ConfigRules(name=n,
                        evaluators=[(None, Pattern("auth.identity.org",
                                                   Operator.EQ, org))])
            for n, org in orgs.items()]


def entries_of(cfgs):
    return [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
            for c in cfgs]


def cdoc(j, org):
    return {"request": {"host": f"h{j}", "path": f"/p{j}", "method": "GET"},
            "auth": {"identity": {"org": org}}}


def make_record(i, name="cfg-a", org="acme", allow=True):
    return {"schema": CAPTURE_SCHEMA, "t": 100.0 + i * 0.01,
            "authconfig": name, "doc": cdoc(i, org),
            "verdict": "allow" if allow else "deny",
            "rule_index": -1 if allow else 0,
            "lane": "engine", "generation": 1}


TH = GuardThresholds(min_requests=8, min_config_requests=4,
                     min_config_allows=2)


@pytest.fixture
def capture():
    """Arm the process-wide capture log (ring only) and restore it."""
    CAPTURE.configure(enabled=True, size_mb=4, sample_n=1)
    CAPTURE.clear()
    yield CAPTURE
    CAPTURE.configure(enabled=False)
    CAPTURE.directory = None
    CAPTURE.clear()


# ---------------------------------------------------------------------------
# capture: bounded memory, sampling, container round trip
# ---------------------------------------------------------------------------


def test_capture_ring_byte_cap_under_sustained_append():
    log = CaptureLog(enabled=True, size_mb=0.01)  # ~10 KB budget
    for i in range(500):
        log.offer("cfg", cdoc(i, "acme" * 10), -1, "engine", 1)
        if i % 50 == 0:
            log.flush()
    assert log.flush()
    assert log._ring_bytes <= log.size_bytes
    assert log.evicted_total > 0          # the cap actually bit
    assert log.stored_total == 500        # evictions are not drops
    assert log.dropped_total == 0
    # the ring keeps the NEWEST records (oldest evicted first)
    recs = log.ring_records()
    assert recs[-1]["doc"]["request"]["host"] == "h499"


def test_capture_queue_overflow_drops_and_counts():
    log = CaptureLog(enabled=True, queue_max=16)
    for i in range(64):  # never drained: the queue must bound itself
        log.offer("cfg", cdoc(i, "acme"), -1, "engine", 1)
    assert len(log._queue) <= 17  # bounded (±1 for the racy len check)
    assert log.dropped_total >= 47
    log.flush()
    assert log.stored_total + log.dropped_total == 64


def test_capture_disabled_is_inert():
    log = CaptureLog(enabled=False)
    log.offer("cfg", cdoc(0, "acme"), -1, "engine", 1)
    assert log.sample_indices(100) == ()
    assert not log._queue and log.stored_total == 0


def test_capture_stride_sampling():
    log = CaptureLog(enabled=True, sample_n=8)
    fired = sum(len(list(log.sample_indices(10))) for _ in range(100))
    assert 100 <= fired <= 150  # 1000 decisions at 1-in-8: ~125
    # sample_n=1 keeps everything
    log2 = CaptureLog(enabled=True, sample_n=1)
    assert list(log2.sample_indices(5)) == [0, 1, 2, 3, 4]


def test_capture_container_round_trip_bit_parity(tmp_path):
    records = [make_record(i, allow=(i % 3 != 0)) for i in range(25)]
    path = str(tmp_path / f"seg{cap_mod.SEGMENT_SUFFIX}")
    write_segment(path, records, meta={"note": "test"})
    header, rt = read_segment(path)
    assert rt == records                        # bit-parity (dict level)
    assert header["schema"] == CAPTURE_SCHEMA
    assert header["count"] == 25
    # canonical encoding parity: re-serializing the round-tripped records
    # yields byte-identical lines
    assert [cap_mod.encode_record(r) for r in rt] == \
        [cap_mod.encode_record(r) for r in records]


def test_capture_container_rejects_corruption_typed(tmp_path):
    path = str(tmp_path / f"seg{cap_mod.SEGMENT_SUFFIX}")
    write_segment(path, [make_record(0)])
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(CaptureFormatError):
        read_segment(path)
    # truncation
    open(path, "wb").write(bytes(blob[:10]))
    with pytest.raises(CaptureFormatError):
        read_segment(path)


def _skewed_container(header: dict) -> bytes:
    hb = json.dumps(header).encode()
    body = cap_mod.MAGIC + struct.pack("<Q", len(hb)) + hb
    return body + hashlib.sha256(body).digest()


def test_capture_container_rejects_version_and_schema_skew(tmp_path):
    p1 = str(tmp_path / "ver.atpucap")
    open(p1, "wb").write(_skewed_container(
        {"version": 999, "schema": CAPTURE_SCHEMA, "count": 0}))
    with pytest.raises(CaptureFormatError, match="version"):
        read_segment(p1)
    p2 = str(tmp_path / "sch.atpucap")
    open(p2, "wb").write(_skewed_container(
        {"version": cap_mod.CAPTURE_FORMAT_VERSION, "schema": 999,
         "count": 0}))
    with pytest.raises(CaptureFormatError, match="schema skew"):
        read_segment(p2)


def test_capture_directory_rotation_and_read(tmp_path):
    d = str(tmp_path / "cap")
    log = CaptureLog(enabled=True, size_mb=1.0)
    log.configure(directory=d, segment_mb=0.004)  # ~4 KB segments
    for i in range(120):
        log.offer("cfg-a", cdoc(i, "acme"), -1, "engine", 1)
    assert log.flush()
    segs = [n for n in os.listdir(d) if n.endswith(cap_mod.SEGMENT_SUFFIX)]
    assert len(segs) >= 2                 # rotation happened
    records = read_capture(d)
    assert len(records) == 120            # nothing lost across segments
    hosts = [r["doc"]["request"]["host"] for r in records]
    assert hosts == [f"h{i}" for i in range(120)]  # oldest-first order


def test_capture_directory_pruned_to_byte_budget(tmp_path):
    d = str(tmp_path / "cap")
    log = CaptureLog(enabled=True, size_mb=0.01)   # ~10 KB total budget
    log.configure(directory=d, segment_mb=0.004)
    for i in range(400):
        log.offer("cfg-a", cdoc(i, "acme" * 8), -1, "engine", 1)
    assert log.flush()
    total = sum(os.path.getsize(os.path.join(d, n))
                for n in os.listdir(d)
                if n.endswith(cap_mod.SEGMENT_SUFFIX))
    # pruned to ~the budget (the newest segment is never pruned, so allow
    # one segment of slack)
    assert total <= log.size_bytes + log.segment_bytes
    assert log.segments_pruned > 0


# ---------------------------------------------------------------------------
# replay: verdict diff on a planted mutation
# ---------------------------------------------------------------------------


def test_replay_diff_planted_one_rule_mutation():
    old = compile_corpus(org_corpus({"cfg-a": "acme", "cfg-b": "beta"}),
                         members_k=4)
    new = compile_corpus(org_corpus({"cfg-a": "nobody", "cfg-b": "beta"}),
                         members_k=4)
    records = [make_record(i, name="cfg-a", org="acme")
               for i in range(10)] + \
              [make_record(i, name="cfg-b", org="evil") for i in range(10)]
    report = replay_records(old, new, records)
    assert report["replayed"] == 20
    assert report["flips"] == {"newly_denied": 10, "newly_allowed": 0,
                               "total": 10}
    # exactly the mutated rule attributed, nothing else
    assert len(report["by_rule"]) == 1
    g = report["by_rule"][0]
    assert g["authconfig"] == "cfg-a"
    assert g["direction"] == "newly-denied"
    assert g["rule_index"] == 0 and "nobody" in g["rule"]
    assert g["count"] == 10 and g["examples"]
    assert report["per_config"]["cfg-a"]["newly_denied"] == 10
    assert report["per_config"]["cfg-b"]["newly_denied"] == 0
    assert report["load_model"] == "replay"
    assert report["platform"].startswith("host-oracle")


def test_replay_diff_clean_churn_is_empty():
    orgs = {"cfg-a": "acme", "cfg-b": "beta"}
    old = compile_corpus(org_corpus(orgs), members_k=4)
    new = compile_corpus(org_corpus(orgs), members_k=4)  # fresh objects
    records = [make_record(i, name="cfg-a", org="acme") for i in range(12)]
    report = replay_records(old, new, records)
    assert report["flips"]["total"] == 0 and report["by_rule"] == []
    assert pregate_check(report, TH) is None


def test_replay_newly_allowed_attributes_the_old_rule():
    old = compile_corpus(org_corpus({"cfg-a": "acme"}), members_k=4)
    new = compile_corpus(org_corpus({"cfg-a": "evil"}), members_k=4)
    records = [make_record(i, name="cfg-a", org="evil", allow=False)
               for i in range(10)]
    report = replay_records(old, new, records)
    assert report["flips"]["newly_allowed"] == 10
    g = report["by_rule"][0]
    assert g["direction"] == "newly-allowed"
    assert "acme" in g["rule"]  # the OLD side's rule — the one that fired


def test_replay_missing_config_and_truncation_are_reported():
    old = compile_corpus(org_corpus({"cfg-a": "acme"}), members_k=4)
    new = compile_corpus(org_corpus({"cfg-a": "acme"}), members_k=4)
    records = [make_record(0), make_record(1, name="ghost")]
    report = replay_records(old, new, records)
    assert report["replayed"] == 1
    assert report["skipped"]["missing_config"] == 1
    assert report["skipped"]["configs_missing_old"] == ["ghost"]
    # zero budget: everything past record 0 reports as truncated
    report2 = replay_records(old, new,
                             [make_record(i) for i in range(100)],
                             time_budget_s=0.0)
    assert report2["skipped"]["truncated"] > 0
    assert report2["replayed"] + report2["skipped"]["truncated"] == 100


def test_pregate_check_judges_with_guard_semantics():
    base = {"replayed": 100,
            "flips": {"newly_denied": 50, "newly_allowed": 0, "total": 50},
            "per_config": {"cfg-a": {"replayed": 50, "newly_denied": 50,
                                     "newly_allowed": 0, "old_allows": 50,
                                     "new_allows": 0}},
            "by_rule": [{"authconfig": "cfg-a", "direction": "newly-denied",
                         "rule_index": 0, "rule": "0:x", "count": 50}],
            "skipped": {"truncated": 0}}
    b = pregate_check(base, TH, changed={"cfg-a"})
    assert b is not None and "cfg-a" in b["suspects"]
    assert "replay-deny-rate" in b["guards"]
    assert b["top_flips"]
    # the changed-set restriction: an unchanged config cannot be a suspect
    b2 = pregate_check(base, TH, changed={"other"})
    assert b2 is None or "cfg-a" not in b2["suspects"]
    # below the evidence floor: no verdict at all
    small = dict(base, replayed=4)
    assert pregate_check(small, TH) is None


def test_pregate_catches_config_confined_loosening():
    """A changed config flipping ALL its denies to allows lowers every
    deny-side rate — the per-config flip-rate criterion must still name
    it (review finding: deny-side-only guards were blind to loosening)."""
    report = {"replayed": 1000,
              "flips": {"newly_denied": 0, "newly_allowed": 20,
                        "total": 20},
              "per_config": {
                  "payments": {"replayed": 20, "newly_denied": 0,
                               "newly_allowed": 20, "old_allows": 0,
                               "new_allows": 20},
                  "other": {"replayed": 980, "newly_denied": 0,
                            "newly_allowed": 0, "old_allows": 900,
                            "new_allows": 900}},
              "by_rule": [{"authconfig": "payments",
                           "direction": "newly-allowed", "rule_index": 0,
                           "rule": "0:x", "count": 20}],
              "skipped": {"truncated": 0}}
    b = pregate_check(report, TH, changed={"payments"})
    assert b is not None and b["suspects"] == ["payments"]
    assert "replay-config-deny-rate" in b["guards"]


def test_pregate_insufficient_replayed_records_skips_not_passes(capture):
    """Ring full of records the candidate cannot re-decide (every config
    renamed) must record 'skipped' — never a false 'pass' that tightens
    the canary guards on zero evidence (review finding)."""
    engine = build_engine(org_corpus({"cfg-a": "acme"}),
                          canary_fraction=0.25, canary_window_s=30.0,
                          canary_thresholds=TH, replay_pregate=True)
    run(_serve(engine, 20, names=("cfg-a",)))
    assert capture.flush()
    engine.apply_snapshot(entries_of(org_corpus({"cfg-x": "acme"})))
    phase = engine._canary
    try:
        assert engine._last_pregate["result"] == "skipped"
        assert engine._last_pregate["replayed"] == 0
        assert phase is not None
        assert phase.preflight["result"] == "skipped"
        # guards NOT tightened on absent evidence
        assert phase.guard.thresholds.deny_delta == TH.deny_delta
    finally:
        engine.canary_promote()


# ---------------------------------------------------------------------------
# engine: capture hook + pregate end to end
# ---------------------------------------------------------------------------


def build_engine(cfgs=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("lane_select", False)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    if cfgs is not None:
        engine.apply_snapshot(entries_of(cfgs))
    return engine


async def _serve(engine, n=40, names=("cfg-a", "cfg-b")):
    for j in range(n):
        org = "acme" if j % 2 == 0 else "evil"
        name = names[j % len(names)]
        await engine.submit(cdoc(j, org), name)


def test_engine_capture_hook_records_full_fidelity(capture):
    engine = build_engine(org_corpus({"cfg-a": "acme", "cfg-b": "beta"}))
    run(_serve(engine, 20))
    assert capture.flush()
    recs = capture.ring_records()
    assert len(recs) == 20
    by_cfg = {r["authconfig"] for r in recs}
    assert by_cfg == {"cfg-a", "cfg-b"}
    r = next(r for r in recs if r["authconfig"] == "cfg-a")
    assert r["schema"] == CAPTURE_SCHEMA
    assert r["verdict"] == "allow" and r["rule_index"] == -1
    assert r["doc"]["auth"]["identity"]["org"] == "acme"
    assert r["generation"] == engine.generation
    d = next(r for r in recs if r["authconfig"] == "cfg-b")
    assert d["verdict"] == "deny" and d["rule_index"] == 0


def test_pregate_rejects_poison_with_zero_live_exposure(capture):
    engine = build_engine(org_corpus({"cfg-a": "acme", "cfg-b": "beta"}),
                          canary_fraction=0.25, canary_window_s=30.0,
                          canary_thresholds=TH, replay_pregate=True)
    run(_serve(engine))
    assert capture.flush()
    gen_before = engine.generation
    poison = org_corpus({"cfg-a": "nobody", "cfg-b": "beta"})
    events_before = RECORDER.events_total
    with pytest.raises(SnapshotRejected) as ei:
        engine.apply_snapshot(entries_of(poison))
    # the typed rejection carries the attributed diff
    assert ei.value.replay_diff["suspects"] == ["cfg-a"]
    assert any("cfg-a" in f and "newly-denied" in f
               for f in ei.value.findings)
    # zero live exposure: no canary started, generation unmoved, and the
    # OLD snapshot still answers with the OLD semantics
    assert engine._canary is None
    assert engine.generation == gen_before
    rule, _ = run(engine.submit(cdoc(0, "acme"), "cfg-a"))
    assert bool(rule[0]) is True
    assert engine._last_pregate["result"] == "breach"
    # the anomaly event rode the flight recorder ring
    assert "replay-pregate-breach" in ANOMALY_KINDS
    with RECORDER._ring_lock:
        kinds = [e["kind"] for e in RECORDER._ring]
    assert "replay-pregate-breach" in kinds
    assert RECORDER.events_total > events_before


def test_pregate_clean_churn_proceeds_to_tightened_canary(capture):
    engine = build_engine(org_corpus({"cfg-a": "acme", "cfg-b": "beta"}),
                          canary_fraction=0.25, canary_window_s=30.0,
                          canary_thresholds=TH, replay_pregate=True)
    run(_serve(engine))
    assert capture.flush()
    # benign churn: cfg-b's captured traffic was denied on both sides
    engine.apply_snapshot(entries_of(
        org_corpus({"cfg-a": "acme", "cfg-b": "gamma"})))
    phase = engine._canary
    try:
        assert phase is not None, "clean preflight must proceed to canary"
        assert phase.preflight["result"] == "pass"
        assert phase.preflight["flips_total"] == 0
        assert phase.preflight["guards_tightened"] is True
        # halved deny deltas on the phase's guard
        assert phase.guard.thresholds.deny_delta == TH.deny_delta / 2
        assert phase.guard.thresholds.config_deny_delta == \
            TH.config_deny_delta / 2
        assert phase.to_json()["preflight"]["result"] == "pass"
        assert engine._last_pregate["result"] == "pass"
    finally:
        engine.canary_promote()


def test_pregate_skips_on_empty_ring_and_swap_proceeds(capture):
    engine = build_engine(org_corpus({"cfg-a": "acme"}),
                          canary_fraction=0.0, replay_pregate=True,
                          canary_thresholds=TH)
    capture.clear()  # nothing captured
    engine.apply_snapshot(entries_of(org_corpus({"cfg-a": "other"})))
    assert engine._last_pregate["result"] == "skipped"
    assert "min_requests" in engine._last_pregate["reason"]
    # the swap landed (skipped ≠ rejected)
    rule, _ = run(engine.submit(cdoc(0, "other"), "cfg-a"))
    assert bool(rule[0]) is True


def test_pregate_without_canary_still_rejects_poison(capture):
    engine = build_engine(org_corpus({"cfg-a": "acme"}),
                          canary_fraction=0.0, replay_pregate=True,
                          canary_thresholds=TH)
    run(_serve(engine, 20, names=("cfg-a",)))
    assert capture.flush()
    with pytest.raises(SnapshotRejected):
        engine.apply_snapshot(entries_of(org_corpus({"cfg-a": "nobody"})))
    rule, _ = run(engine.submit(cdoc(0, "acme"), "cfg-a"))
    assert bool(rule[0]) is True


def test_engine_debug_vars_carries_replay_block(capture):
    engine = build_engine(org_corpus({"cfg-a": "acme"}),
                          replay_pregate=True)
    dv = engine.debug_vars()["replay"]
    assert dv["pregate"]["enabled"] is True
    assert dv["capture"]["enabled"] is True
    json.dumps(dv)  # JSON-safe


# ---------------------------------------------------------------------------
# decision-record schema satellites
# ---------------------------------------------------------------------------


def test_decision_records_are_schema_stamped():
    log = prov_mod.DecisionLog(capacity=4, sample_n=1)
    log.record(lane="engine", host="h", authconfig="c", verdict=True,
               rule=None, rule_index=-1, latency_ms=0.1, generation=1)
    rec = log.to_json()["records"][-1]
    assert rec["schema"] == prov_mod.DECISION_SCHEMA
    assert tuple(sorted(rec)) == tuple(sorted(prov_mod.DECISION_FIELDS))


def test_decision_schema_skew_rejected_typed():
    ok = {"schema": prov_mod.DECISION_SCHEMA, "records": []}
    prov_mod.check_decision_schema(ok)  # no raise
    for bad in ({"schema": 1, "records": []}, {"records": []}, []):
        with pytest.raises(prov_mod.DecisionSchemaError):
            prov_mod.check_decision_schema(bad)


def test_analysis_decisions_reader_rejects_skew(tmp_path, capsys):
    from authorino_tpu.analysis.__main__ import main as analysis_main

    p = str(tmp_path / "decisions.json")
    json.dump({"schema": 1, "records": [], "capacity": 8, "sample_n": 1,
               "records_total": 0}, open(p, "w"))
    assert analysis_main(["--decisions", p]) == 1
    assert "DecisionSchemaError" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# bench load model + offline CLI
# ---------------------------------------------------------------------------


def test_bench_load_timetable(tmp_path):
    from authorino_tpu.replay.bench_load import load_timetable

    d = str(tmp_path / "cap")
    os.makedirs(d)
    records = [make_record(i) for i in range(20)]
    records.reverse()  # out of order on disk: the loader must sort
    write_segment(os.path.join(d, f"s1{cap_mod.SEGMENT_SUFFIX}"), records)
    offsets, names, docs, meta = load_timetable(d, speed=2.0)
    assert offsets[0] == 0.0
    assert offsets == sorted(offsets)
    assert meta["records"] == 20 and meta["speed"] == 2.0
    # 19 gaps of 10 ms at 2x speed → ~95 ms span
    assert abs(offsets[-1] - 0.095) < 1e-6
    assert names[0] == "cfg-a" and docs[0]["request"]["host"] == "h0"
    offs2, *_ = load_timetable(d, limit=5)
    assert len(offs2) == 5


def test_analysis_replay_cli_offline(tmp_path, capsys):
    from authorino_tpu.analysis.__main__ import main as analysis_main
    from authorino_tpu.snapshots import rules_fingerprint, serialize_policy

    def blob(path, orgs, gen):
        cfgs = org_corpus(orgs)
        fps = {c.name: rules_fingerprint(c) for c in cfgs}
        b = serialize_policy(compile_corpus(cfgs, members_k=4),
                             meta={"fingerprints": fps, "certified": True,
                                   "generation": gen})
        open(path, "wb").write(b)

    old_p = str(tmp_path / "old.atpusnap")
    new_p = str(tmp_path / "new.atpusnap")
    blob(old_p, {"cfg-a": "acme", "cfg-b": "beta"}, 1)
    blob(new_p, {"cfg-a": "nobody", "cfg-b": "beta"}, 2)
    d = str(tmp_path / "cap")
    os.makedirs(d)
    write_segment(os.path.join(d, f"s{cap_mod.SEGMENT_SUFFIX}"),
                  [make_record(i) for i in range(40)])
    rc = analysis_main(["--replay", old_p, new_p, "--log", d, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1  # flips present
    assert report["flips"]["newly_denied"] == 40
    assert report["by_rule"][0]["authconfig"] == "cfg-a"
    assert report["pregate"] and "cfg-a" in report["pregate"]["suspects"]
    # clean pair exits 0
    rc2 = analysis_main(["--replay", old_p, old_p, "--log", d, "--json"])
    report2 = json.loads(capsys.readouterr().out)
    assert rc2 == 0 and report2["flips"]["total"] == 0


def test_debug_replay_endpoint(capture):
    from aiohttp.test_utils import TestClient, TestServer

    from authorino_tpu.service.http_server import build_app

    engine = build_engine(org_corpus({"cfg-a": "acme"}))
    run(_serve(engine, 4, names=("cfg-a",)))
    capture.flush()

    async def body():
        client = TestClient(TestServer(build_app(engine)))
        await client.start_server()
        try:
            resp = await client.get("/debug/replay")
            assert resp.status == 200
            payload = await resp.json()
        finally:
            await client.close()
        return payload

    payload = run(body())
    assert payload["capture"]["enabled"] is True
    assert payload["capture"]["stored_total"] >= 4
    assert payload["pregate"]["enabled"] is False
