"""rego_lower: the lowered pattern Expression must agree with the mini-Rego
interpreter on EVERY input — randomized differential sweeps over docs with
missing keys, empty strings, and adversarial header values — and must refuse
anything outside the provably-equivalent subset."""

import random

import pytest

from authorino_tpu.authjson.wellknown import (
    CheckRequestModel,
    HttpRequestAttributes,
    build_authorization_json,
)
from authorino_tpu.evaluators.authorization import OPA, rego
from authorino_tpu.evaluators.authorization.rego_lower import lower_verdict


def compile_allow(src: str) -> rego.RegoModule:
    return rego.compile_module(f"default allow = false\n{src}", package="t")


def interp_allow(module: rego.RegoModule, doc) -> bool:
    return bool(module.evaluate(doc).get("allow"))


def rand_doc(rng: random.Random):
    methods = ["GET", "POST", "DELETE", "OPTIONS", ""]
    paths = ["/", "/api/v1", "/apix", "/admin", "/a b", ""]
    header_pool = [
        ("x-root", ["true", "false", "", "TRUE"]),
        ("x-tier", ["t-1", "t-2", "", "t"]),
        ("x-org", ["acme", "evil", "ac", ""]),
    ]
    headers = {}
    for name, vals in header_pool:
        if rng.random() < 0.7:
            headers[name] = rng.choice(vals)
    req = CheckRequestModel(http=HttpRequestAttributes(
        method=rng.choice(methods), path=rng.choice(paths),
        host="h.test", scheme=rng.choice(["http", "https", ""]),
        headers=headers))
    return build_authorization_json(req)


LOWERABLE = [
    'allow { input.request.method == "GET" }',
    ('allow { input.request.method == "GET" }\n'
     'allow { input.request.headers["x-root"] == "true" }'),
    'allow { "POST" == input.request.method }',
    'allow { input.request.method != "DELETE" }',
    'allow { not input.request.headers["x-org"] == "evil" }',
    'allow { not input.request.method != "GET" }',
    'allow { startswith(input.request.path, "/api") }',
    'allow { endswith(input.request.path, "/v1") }',
    'allow { contains(input.request.path, "admin") }',
    'allow { regex.match("^t-[0-9]+$", input.request.headers["x-tier"]) }',
    ('allow { input.request.method == "GET"; '
     'input.request.headers["x-tier"] == "t-1" }'),
    'allow { input.request.scheme == "" }',   # always-present, empty const ok
    'allow { true }',
    'allow { input.request.method = "GET" }',  # unification form
    # statically-false body: the rule contributes nothing
    'allow { 1 == 2 }\nallow { input.request.method == "GET" }',
]


@pytest.mark.parametrize("src", LOWERABLE)
def test_lowered_matches_interpreter(src):
    module = compile_allow(src)
    expr = lower_verdict(module)
    assert expr is not None, f"must lower: {src}"
    rng = random.Random(hash(src) & 0xFFFF)
    for _ in range(200):
        doc = rand_doc(rng)
        assert expr.matches(doc) == interp_allow(module, doc), (
            f"divergence for {src!r} on "
            f"method={doc['request']['method']!r} "
            f"path={doc['request']['path']!r} "
            f"headers={doc['request']['headers']!r}")


NOT_LOWERABLE = [
    # maybe-missing selector with != (missing: Rego false, pattern true)
    'allow { input.request.headers["x-root"] != "true" }',
    # maybe-missing selector, == "" (missing: Rego false, pattern true)
    'allow { input.request.headers["x-root"] == "" }',
    # not(!=) on maybe-missing
    'allow { not input.request.headers["x-root"] != "true" }',
    # regex matching "" on a maybe-missing selector
    'allow { regex.match("a*", input.request.headers["x-root"]) }',
    # numeric path value (string-typed selector vs int const: Rego's
    # TypeError→False branch has no pattern equivalent)
    'allow { input.request.method == 3 }',
    # ordered comparison on a string-typed selector (same reason) — only
    # the provably-int paths (_INT_SCALARS) ride the numeric lane
    'allow { input.request.headers["x-n"] > 3 }',
    'allow { input.request.method > 3 }',
    # != on a maybe-missing int path (missing: Rego false, pattern true)
    'allow { input.source.port != 80 }',
    # not(cmp) on a maybe-missing int path (inner undefined → Rego true,
    # numeric patterns read False on "")
    'allow { not input.source.port > 80 }',
    # float const: the numeric lane is integer-only
    'allow { input.request.size > 1.5 }',
    # auth.* (identity values not provably strings)
    'allow { input.auth.identity.sub == "x" }',
    # data refs
    'allow { data.roles[_] == "x" }',
    # helper rules could error / matter
    'ok { input.request.method == "GET" }\nallow { ok }',
    # functions
    'f(x) = y { y := x }\nallow { f("a") == "a" }',
    # else chains
    'allow { input.request.method == "GET" } else = true { true }',
    # non-true rule value
    'allow = "yes" { input.request.method == "GET" }',
    # arbitrary builtins
    'allow { count(input.request.headers) > 0 }',
    # invalid regex (interpreter raises → fail-closed; must not lower)
    'allow { regex.match("(", input.request.method) }',
]


@pytest.mark.parametrize("src", NOT_LOWERABLE)
def test_refuses_outside_subset(src):
    module = compile_allow(src)
    assert lower_verdict(module) is None, f"must NOT lower: {src}"


def test_opa_evaluator_gates_lowering():
    ev = OPA("t/a", inline_rego='allow { input.request.method == "GET" }')
    assert ev.lowered_verdict() is not None
    proc = OPA("t/b", inline_rego='allow { count(input.request.headers) > 0 }')
    assert proc.lowered_verdict() is None
    # external policies hot-swap without reconcile: never lowered
    from authorino_tpu.evaluators.authorization import OPAExternalSource

    ext = OPA("t/c", external_source=OPAExternalSource("http://x"))
    ext.precompile('allow { input.request.method == "GET" }')
    assert ext.lowered_verdict() is None


def test_unsatisfiable_and_empty_policies_lower_to_false():
    module = compile_allow('allow { 1 == 2 }')
    expr = lower_verdict(module)
    assert expr is not None
    rng = random.Random(7)
    for _ in range(20):
        assert expr.matches(rand_doc(rng)) is False
