# authorino-tpu serving image (parity: ref Dockerfile:8 — the reference
# builds a static Go binary; here the image carries the Python package, the
# native C++ batch encoder prebuilt from source, and jax from the base
# image).
#
# Build from a jax-enabled base so the TPU runtime libraries match the host
# (on GKE TPU node pools use the cloud-tpu base; for CPU-only serving any
# python base works with JAX_PLATFORM=cpu).
ARG BASE_IMAGE=python:3.11-slim
FROM ${BASE_IMAGE} AS build

RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY pyproject.toml README.md ./
COPY authorino_tpu ./authorino_tpu
COPY native ./native

RUN pip install --no-cache-dir .

# Prebuild the native batch encoder at the path the loader probes
# (authorino_tpu/native/__init__.py: <pkg>/native/_build/_atpuenc.so, with
# sources expected at <site-packages>/native for the staleness check).
# The loader falls back to the pure-Python encoder if any of this is absent.
# Stage into a fixed path: site-packages' real location depends on the base
# image's Python version, so the final stage re-derives it via sysconfig.
RUN SITE=$(python -c "import sysconfig; print(sysconfig.get_paths()['purelib'])") && \
    cp -r native "$SITE/native" && \
    mkdir -p "$SITE/authorino_tpu/native/_build" && \
    g++ -O2 -std=c++17 -shared -fPIC -pthread \
        -I "$(python -c "import sysconfig; print(sysconfig.get_paths()['include'])")" \
        "$SITE/native/pymod.cpp" -ldl \
        -o "$SITE/authorino_tpu/native/_build/_atpuenc.so" && \
    touch "$SITE/authorino_tpu/native/_build/_atpuenc.so" && \
    mkdir -p /staged && cp -a "$SITE" /staged/site-packages && \
    touch /staged/site-packages/authorino_tpu/native/_build/_atpuenc.so && \
    cp /usr/local/bin/authorino-tpu /staged/authorino-tpu

FROM ${BASE_IMAGE}
# libnghttp2 backs the native gRPC frontend (native/frontend.cpp dlopens
# it); absent, the server falls back to the Python grpc.aio listener — so
# the install is best-effort to keep non-apt BASE_IMAGEs buildable
RUN if command -v apt-get >/dev/null; then \
        apt-get update && apt-get install -y --no-install-recommends libnghttp2-14 \
        && rm -rf /var/lib/apt/lists/*; \
    fi \
    && groupadd -r authorino && useradd -r -g authorino -u 1001 authorino
COPY --from=build /staged /staged
RUN python -c "import shutil, sysconfig; \
shutil.copytree('/staged/site-packages', sysconfig.get_paths()['purelib'], dirs_exist_ok=True)" && \
    python -c "import os, sysconfig; \
os.utime(sysconfig.get_paths()['purelib'] + '/authorino_tpu/native/_build/_atpuenc.so')" && \
    install -m 0755 /staged/authorino-tpu /usr/local/bin/authorino-tpu && \
    rm -rf /staged
# the utime keeps the prebuilt .so newer than the staged sources — the
# loader's mtime staleness check must not trigger a rebuild in the
# runtime image (no g++, non-root site-packages → permanent Python fallback)
USER 1001
ENTRYPOINT ["authorino-tpu"]
CMD ["server"]
