// Minimal HTTP/2 gRPC load generator for the native ext_authz frontend.
//
// Prebakes each CheckRequest payload into HEADERS+DATA frame bytes once
// (HPACK literals without indexing → the block is stream-independent, only
// the stream ids get patched), then drives N connections with D concurrent
// streams each from one thread.  Latency is measured per stream from
// enqueue to the grpc trailers frame — the number a real client sees.
//
// The server side is the full nghttp2 stack; this client stays raw on
// purpose: on the 1-core benchmark host, client cycles eat directly into
// the measured server throughput, so the client must be as thin as the
// wire allows (the reference benchmarks pay the same tax in-process via
// go test -bench, ref Makefile:135-142).
//
// Usage: loadgen <host> <port> <payload_file> <seconds> <warmup_s> <depth> <conns>
//   payload_file: repeated [u32 big-endian length][CheckRequest bytes]
// Prints one JSON line on stdout.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <string>
#include <unordered_map>
#include <vector>

static double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

static void be24(std::string& s, uint32_t v) {
  s.push_back((char)(v >> 16));
  s.push_back((char)(v >> 8));
  s.push_back((char)v);
}

static void be32(std::string& s, uint32_t v) {
  s.push_back((char)(v >> 24));
  s.push_back((char)(v >> 16));
  s.push_back((char)(v >> 8));
  s.push_back((char)v);
}

// one request's frames with the two stream-id offsets to patch
struct Baked {
  std::string bytes;
  size_t sid_off1, sid_off2;
};

static Baked bake(const std::string& msg) {
  // HPACK block: literals without indexing, no huffman
  std::string hp;
  hp.push_back((char)0x83);  // :method POST (static 3)
  hp.push_back((char)0x86);  // :scheme http (static 6)
  static const char kPath[] = "/envoy.service.auth.v3.Authorization/Check";
  hp.push_back((char)0x04);  // literal w/o indexing, name = static 4 (:path)
  hp.push_back((char)(sizeof(kPath) - 1));
  hp.append(kPath, sizeof(kPath) - 1);
  hp.push_back((char)0x01);  // :authority (static 1)
  hp.push_back((char)2);
  hp.append("lg", 2);
  hp.push_back((char)0x0f);  // content-type (static 31 = 15 + 16)
  hp.push_back((char)0x10);
  hp.push_back((char)16);
  hp.append("application/grpc", 16);
  hp.push_back((char)0x00);  // te: trailers (new name)
  hp.push_back((char)2);
  hp.append("te", 2);
  hp.push_back((char)8);
  hp.append("trailers", 8);

  Baked b;
  // HEADERS frame
  be24(b.bytes, (uint32_t)hp.size());
  b.bytes.push_back((char)0x01);  // type HEADERS
  b.bytes.push_back((char)0x04);  // END_HEADERS
  b.sid_off1 = b.bytes.size();
  be32(b.bytes, 0);
  b.bytes.append(hp);
  // DATA frame: 5-byte gRPC prefix + message, END_STREAM
  uint32_t dlen = 5 + (uint32_t)msg.size();
  be24(b.bytes, dlen);
  b.bytes.push_back((char)0x00);  // type DATA
  b.bytes.push_back((char)0x01);  // END_STREAM
  b.sid_off2 = b.bytes.size();
  be32(b.bytes, 0);
  b.bytes.push_back((char)0);     // uncompressed
  be32(b.bytes, (uint32_t)msg.size());
  b.bytes.append(msg);
  return b;
}

struct ConnSt {
  int fd = -1;
  std::string out;
  size_t out_off = 0;
  // reader state machine
  uint8_t hdr[9];
  int hdr_got = 0;
  uint32_t frame_len = 0;
  uint8_t frame_type = 0, frame_flags = 0;
  int32_t frame_sid = 0;
  uint32_t payload_left = 0;
  std::vector<uint8_t> payload;  // kept only for SETTINGS/PING
  bool collect_payload = false;
  int32_t next_sid = 1;
  int in_flight = 0;
  std::unordered_map<int32_t, double> t0;
  bool dead = false;
};

static uint64_t g_done = 0, g_errors = 0;
static std::vector<float>* g_lat = nullptr;
static bool g_record = false;

static void stream_done(ConnSt& c, int32_t sid, bool ok) {
  auto it = c.t0.find(sid);
  if (it != c.t0.end()) {
    if (g_record && g_lat) g_lat->push_back((float)((now_s() - it->second) * 1e3));
    c.t0.erase(it);
    c.in_flight--;
    if (g_record) {
      g_done++;
      if (!ok) g_errors++;
    }
  }
}

static void handle_frame(ConnSt& c) {
  switch (c.frame_type) {
    case 0x04:  // SETTINGS
      if (!(c.frame_flags & 0x01)) {
        static const char ack[] = {0, 0, 0, 0x04, 0x01, 0, 0, 0, 0};
        c.out.append(ack, 9);
      }
      break;
    case 0x06:  // PING
      if (!(c.frame_flags & 0x01) && c.payload.size() == 8) {
        std::string f;
        be24(f, 8);
        f.push_back((char)0x06);
        f.push_back((char)0x01);
        be32(f, 0);
        f.append((const char*)c.payload.data(), 8);
        c.out.append(f);
      }
      break;
    case 0x01:  // HEADERS (response or trailers)
      if (c.frame_flags & 0x01) stream_done(c, c.frame_sid, true);
      break;
    case 0x00:  // DATA
      if (c.frame_flags & 0x01) stream_done(c, c.frame_sid, true);
      break;
    case 0x03:  // RST_STREAM
      stream_done(c, c.frame_sid, false);
      break;
    case 0x07:  // GOAWAY
      c.dead = true;
      break;
    default:
      break;
  }
}

static void feed(ConnSt& c, const uint8_t* p, size_t n) {
  while (n) {
    if (c.payload_left) {
      size_t take = n < c.payload_left ? n : c.payload_left;
      if (c.collect_payload) c.payload.insert(c.payload.end(), p, p + take);
      c.payload_left -= (uint32_t)take;
      p += take;
      n -= take;
      if (c.payload_left == 0) handle_frame(c);
      continue;
    }
    size_t need = 9 - c.hdr_got;
    size_t take = n < need ? n : need;
    memcpy(c.hdr + c.hdr_got, p, take);
    c.hdr_got += (int)take;
    p += take;
    n -= take;
    if (c.hdr_got < 9) return;
    c.hdr_got = 0;
    c.frame_len = ((uint32_t)c.hdr[0] << 16) | ((uint32_t)c.hdr[1] << 8) | c.hdr[2];
    c.frame_type = c.hdr[3];
    c.frame_flags = c.hdr[4];
    c.frame_sid = (int32_t)(((uint32_t)c.hdr[5] << 24) | ((uint32_t)c.hdr[6] << 16) |
                            ((uint32_t)c.hdr[7] << 8) | c.hdr[8]) & 0x7fffffff;
    c.payload.clear();
    c.collect_payload = (c.frame_type == 0x04 || c.frame_type == 0x06);
    c.payload_left = c.frame_len;
    if (c.payload_left == 0) handle_frame(c);
  }
}

int main(int argc, char** argv) {
  if (argc < 8) {
    fprintf(stderr,
            "usage: loadgen <host> <port> <payloads> <seconds> <warmup> <depth> <conns>\n");
    return 2;
  }
  const char* host = argv[1];
  int port = atoi(argv[2]);
  FILE* f = fopen(argv[3], "rb");
  if (!f) { perror("payloads"); return 2; }
  double seconds = atof(argv[4]);
  double warmup = atof(argv[5]);
  int depth = atoi(argv[6]);
  int nconns = atoi(argv[7]);

  std::vector<Baked> baked;
  for (;;) {
    uint8_t lb[4];
    if (fread(lb, 1, 4, f) != 4) break;
    uint32_t len = ((uint32_t)lb[0] << 24) | ((uint32_t)lb[1] << 16) |
                   ((uint32_t)lb[2] << 8) | lb[3];
    std::string msg(len, '\0');
    if (fread(&msg[0], 1, len, f) != len) break;
    baked.push_back(bake(msg));
  }
  fclose(f);
  if (baked.empty()) { fprintf(stderr, "no payloads\n"); return 2; }

  std::vector<ConnSt> conns((size_t)nconns);
  for (ConnSt& c : conns) {
    c.fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof addr);
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    inet_pton(AF_INET, host, &addr.sin_addr);
    if (connect(c.fd, (struct sockaddr*)&addr, sizeof addr) < 0) {
      perror("connect");
      return 2;
    }
    int one = 1;
    setsockopt(c.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    fcntl(c.fd, F_SETFL, O_NONBLOCK);
    c.out = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
    // SETTINGS: huge initial window, then a huge connection WINDOW_UPDATE —
    // flow control effectively disabled client-side (responses are tiny)
    std::string st;
    be24(st, 12);
    st.push_back((char)0x04);
    st.push_back((char)0x00);
    be32(st, 0);
    st.push_back(0); st.push_back(0x04); be32(st, 0x7fffffff);  // INITIAL_WINDOW_SIZE
    st.push_back(0); st.push_back(0x03); be32(st, 0x7fffffff);  // MAX_CONCURRENT_STREAMS
    c.out.append(st);
    std::string wu;
    be24(wu, 4);
    wu.push_back((char)0x08);
    wu.push_back((char)0x00);
    be32(wu, 0);
    be32(wu, 0x7fffffff - 65535);
    c.out.append(wu);
  }

  std::vector<float> lat;
  lat.reserve(1 << 22);
  g_lat = &lat;

  size_t pay_i = 0;
  double t_start = now_s();
  double t_measure = t_start + warmup;
  double t_end = t_measure + seconds;
  bool recording = false;
  uint64_t launched = 0;

  std::vector<struct pollfd> pfds((size_t)nconns);
  uint8_t buf[262144];
  for (;;) {
    double now = now_s();
    if (!recording && now >= t_measure) {
      recording = true;
      g_record = true;
      g_done = 0;
      g_errors = 0;
      lat.clear();
      t_measure = now;  // actual start of the measured window
    }
    if (now >= t_end) break;

    // top up each connection's pipeline
    for (ConnSt& c : conns) {
      if (c.dead) continue;
      while (c.in_flight < depth && c.next_sid < 0x7ffffff0 &&
             c.out.size() - c.out_off < (size_t)4 << 20) {
        const Baked& b = baked[pay_i++ % baked.size()];
        size_t base = c.out.size();
        c.out.append(b.bytes);
        uint32_t sid = (uint32_t)c.next_sid;
        uint8_t* p1 = (uint8_t*)&c.out[base + b.sid_off1];
        uint8_t* p2 = (uint8_t*)&c.out[base + b.sid_off2];
        p1[0] = (uint8_t)(sid >> 24); p1[1] = (uint8_t)(sid >> 16);
        p1[2] = (uint8_t)(sid >> 8);  p1[3] = (uint8_t)sid;
        p2[0] = (uint8_t)(sid >> 24); p2[1] = (uint8_t)(sid >> 16);
        p2[2] = (uint8_t)(sid >> 8);  p2[3] = (uint8_t)sid;
        c.t0[(int32_t)sid] = now_s();
        c.next_sid += 2;
        c.in_flight++;
        launched++;
      }
    }

    for (int i = 0; i < nconns; ++i) {
      pfds[i].fd = conns[i].fd;
      pfds[i].events = POLLIN;
      if (conns[i].out_off < conns[i].out.size()) pfds[i].events |= POLLOUT;
    }
    poll(pfds.data(), (nfds_t)nconns, 10);
    for (int i = 0; i < nconns; ++i) {
      ConnSt& c = conns[i];
      if (c.dead) continue;
      if (pfds[i].revents & POLLOUT) {
        ssize_t w = send(c.fd, c.out.data() + c.out_off, c.out.size() - c.out_off,
                         MSG_NOSIGNAL);
        if (w > 0) {
          c.out_off += (size_t)w;
          if (c.out_off == c.out.size()) {
            c.out.clear();
            c.out_off = 0;
          } else if (c.out_off > (size_t)1 << 20) {
            c.out.erase(0, c.out_off);
            c.out_off = 0;
          }
        } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
          c.dead = true;
        }
      }
      if (pfds[i].revents & (POLLIN | POLLHUP)) {
        for (;;) {
          ssize_t r = recv(c.fd, buf, sizeof buf, 0);
          if (r > 0) {
            feed(c, buf, (size_t)r);
            if (r < (ssize_t)sizeof buf) break;
          } else if (r == 0) {
            c.dead = true;
            break;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) c.dead = true;
            break;
          }
        }
      }
    }
  }
  double elapsed = now_s() - t_measure;
  for (ConnSt& c : conns) close(c.fd);

  std::sort(lat.begin(), lat.end());
  auto pct = [&](double q) {
    if (lat.empty()) return 0.0;
    size_t i = (size_t)(q * (lat.size() - 1));
    return (double)lat[i];
  };
  printf(
      "{\"total\": %llu, \"seconds\": %.3f, \"rps\": %.1f, \"p50_ms\": %.3f, "
      "\"p90_ms\": %.3f, \"p99_ms\": %.3f, \"errors\": %llu, \"conns\": %d, "
      "\"depth\": %d}\n",
      (unsigned long long)g_done, elapsed, g_done / elapsed, pct(0.5), pct(0.9),
      pct(0.99), (unsigned long long)g_errors, nconns, depth);
  return 0;
}
