// Native batch encoder: the CPU half of the hot path.
//
// Replaces compiler/encode.py's per-request Python loops (selector walk,
// gjson-String render, intern lookup, tensor scatter) with a multithreaded
// C++ pass over a batch of Authorization-JSON documents.  Semantics must be
// bit-identical to the Python encoder (the reference behavior is gjson
// String()/Array() — ref: pkg/jsonexp/expressions.go:59-96,
// pkg/json/json.go); tests/test_native_encoder.py runs the differential.
//
// ABI (ctypes, see authorino_tpu/native/__init__.py):
//   atpu_policy_new(...)  -> opaque Policy*
//   atpu_policy_free(p)
//   atpu_encode(...)      -> n_cpu_tasks >= 0, or <0 => caller falls back
//
// Only plain dot-path selectors are resolved here ("key" segments — the
// overwhelming majority); attrs with gjson-extended selectors (#, queries,
// @modifiers) are flagged complex by the wrapper and finished in Python.
//
// Build: g++ -O2 -shared -fPIC -pthread -std=c++17 encoder.cpp -o libatpuenc.so

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// op codes — must match authorino_tpu/compiler/compile.py
enum {
  OP_EQ = 0, OP_NEQ = 1, OP_INCL = 2, OP_EXCL = 3,
  OP_CPU = 4, OP_ERROR = 5, OP_TREE_CPU = 6, OP_REGEX_DFA = 7,
};
constexpr int32_t UNSEEN = -2;

// ---------------------------------------------------------------------------
// interner: open-addressing read-only hash table (string -> id)
// ---------------------------------------------------------------------------
struct Interner {
  struct Slot { const char* p; int32_t len; int32_t id; };
  std::vector<Slot> slots;
  uint64_t mask = 0;

  static uint64_t hash(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;  // FNV-1a
    for (size_t i = 0; i < n; ++i) { h ^= (uint8_t)s[i]; h *= 1099511628211ull; }
    return h;
  }

  void build(const char* blob, const int64_t* offs, const int32_t* ids, int32_t n) {
    size_t cap = 16;
    while (cap < (size_t)n * 2) cap <<= 1;
    slots.assign(cap, Slot{nullptr, 0, UNSEEN});
    mask = cap - 1;
    for (int32_t i = 0; i < n; ++i) {
      const char* p = blob + offs[i];
      int32_t len = (int32_t)(offs[i + 1] - offs[i]);
      uint64_t h = hash(p, (size_t)len) & mask;
      while (slots[h].p != nullptr) h = (h + 1) & mask;
      slots[h] = Slot{p, len, ids[i]};
    }
  }

  int32_t lookup(const char* s, size_t n) const {
    uint64_t h = hash(s, n) & mask;
    for (;;) {
      const Slot& sl = slots[h];
      if (sl.p == nullptr) return UNSEEN;
      if ((size_t)sl.len == n && memcmp(sl.p, s, n) == 0) return sl.id;
      h = (h + 1) & mask;
    }
  }
};

// ---------------------------------------------------------------------------
// JSON DOM (arena) — parses json.dumps output plus NaN/Infinity tokens
// ---------------------------------------------------------------------------
enum VType : uint8_t { V_NULL, V_FALSE, V_TRUE, V_INT, V_DBL, V_STR, V_ARR, V_OBJ };

struct Node {
  uint8_t type;
  uint8_t key_decoded;   // key lives in decode arena (had escapes)
  uint8_t str_decoded;   // string/int-token arena flag
  int32_t nchildren;
  int64_t str_off; int32_t str_len;   // V_STR text / V_INT raw token
  int64_t key_off; int32_t key_len;   // object-member key
  double dbl;
  int32_t first_child;   // node index, -1 none
  int32_t next_sibling;  // node index, -1 none
};

struct Doc {
  std::vector<Node>* nodes;
  std::string* decode;     // decoded (escaped) strings
  const char* blob;        // raw json text

  const char* str(const Node& n) const { return (n.str_decoded ? decode->data() : blob) + n.str_off; }
  const char* key(const Node& n) const { return (n.key_decoded ? decode->data() : blob) + n.key_off; }
};

struct Parser {
  const char* p;
  const char* end;
  std::vector<Node>& nodes;
  std::string& decode;
  const char* blob;
  bool ok = true;

  void skip_ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p; }

  // returns node index or -1
  int32_t parse_value() {
    skip_ws();
    if (p >= end) { ok = false; return -1; }
    char c = *p;
    if (c == '{') return parse_obj();
    if (c == '[') return parse_arr();
    if (c == '"') return parse_str();
    if (c == 't') { return lit("true", V_TRUE); }
    if (c == 'f') { return lit("false", V_FALSE); }
    if (c == 'n') { return lit("null", V_NULL); }
    if (c == 'N') { return lit_dbl("NaN", NAN); }
    if (c == 'I') { return lit_dbl("Infinity", INFINITY); }
    if (c == '-' && p + 1 < end && p[1] == 'I') { return lit_dbl("-Infinity", -INFINITY); }
    return parse_num();
  }

  int32_t lit(const char* s, uint8_t t) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) { ok = false; return -1; }
    p += n;
    return push(t);
  }
  int32_t lit_dbl(const char* s, double v) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) { ok = false; return -1; }
    p += n;
    int32_t i = push(V_DBL);
    nodes[i].dbl = v;
    return i;
  }

  int32_t push(uint8_t t) {
    Node n{};
    n.type = t;
    n.first_child = -1;
    n.next_sibling = -1;
    nodes.push_back(n);
    return (int32_t)nodes.size() - 1;
  }

  int32_t parse_num() {
    const char* start = p;
    if (p < end && *p == '-') ++p;
    bool is_int = true;
    while (p < end && ((*p >= '0' && *p <= '9'))) ++p;
    if (p < end && *p == '.') { is_int = false; ++p; while (p < end && *p >= '0' && *p <= '9') ++p; }
    if (p < end && (*p == 'e' || *p == 'E')) {
      is_int = false; ++p;
      if (p < end && (*p == '+' || *p == '-')) ++p;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p == start || (*start == '-' && p == start + 1)) { ok = false; return -1; }
    int32_t i;
    if (is_int) {
      // big ints render as their own token (Python str(int) == token for
      // canonical JSON ints); "-0" is the one non-canonical case
      i = push(V_INT);
      if (p - start == 2 && start[0] == '-' && start[1] == '0') {
        nodes[i].str_off = start + 1 - blob;  // "-0" -> "0"
        nodes[i].str_len = 1;
      } else {
        nodes[i].str_off = start - blob;
        nodes[i].str_len = (int32_t)(p - start);
      }
      nodes[i].str_decoded = 0;
    } else {
      double v = strtod(start, nullptr);
      i = push(V_DBL);
      nodes[i].dbl = v;
    }
    return i;
  }

  // decode a JSON string starting at '"'; returns (off, len, decoded_flag)
  bool scan_string(int64_t* off, int32_t* len, uint8_t* decoded) {
    ++p;  // opening quote
    const char* start = p;
    bool has_escape = false;
    while (p < end && *p != '"') {
      if (*p == '\\') { has_escape = true; ++p; if (p >= end) return false; }
      ++p;
    }
    if (p >= end) return false;
    if (!has_escape) {
      *off = start - blob;
      *len = (int32_t)(p - start);
      *decoded = 0;
      ++p;
      return true;
    }
    size_t out_start = decode.size();
    const char* q = start;
    while (q < p) {
      if (*q != '\\') { decode.push_back(*q++); continue; }
      ++q;
      switch (*q) {
        case '"': decode.push_back('"'); ++q; break;
        case '\\': decode.push_back('\\'); ++q; break;
        case '/': decode.push_back('/'); ++q; break;
        case 'b': decode.push_back('\b'); ++q; break;
        case 'f': decode.push_back('\f'); ++q; break;
        case 'n': decode.push_back('\n'); ++q; break;
        case 'r': decode.push_back('\r'); ++q; break;
        case 't': decode.push_back('\t'); ++q; break;
        case 'u': {
          ++q;
          if (p - q < 4) return false;
          uint32_t cp = 0;
          for (int k = 0; k < 4; ++k) {
            char h = q[k]; cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return false;
          }
          q += 4;
          if (cp >= 0xD800 && cp <= 0xDBFF && p - q >= 6 && q[0] == '\\' && q[1] == 'u') {
            uint32_t lo = 0;
            bool okp = true;
            for (int k = 0; k < 4; ++k) {
              char h = q[2 + k]; lo <<= 4;
              if (h >= '0' && h <= '9') lo |= h - '0';
              else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
              else { okp = false; break; }
            }
            if (okp && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              q += 6;
            }
          }
          // UTF-8 encode
          if (cp < 0x80) decode.push_back((char)cp);
          else if (cp < 0x800) {
            decode.push_back((char)(0xC0 | (cp >> 6)));
            decode.push_back((char)(0x80 | (cp & 0x3F)));
          } else if (cp < 0x10000) {
            decode.push_back((char)(0xE0 | (cp >> 12)));
            decode.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            decode.push_back((char)(0x80 | (cp & 0x3F)));
          } else {
            decode.push_back((char)(0xF0 | (cp >> 18)));
            decode.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
            decode.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
            decode.push_back((char)(0x80 | (cp & 0x3F)));
          }
          break;
        }
        default: return false;
      }
    }
    *off = (int64_t)out_start;
    *len = (int32_t)(decode.size() - out_start);
    *decoded = 1;
    ++p;
    return true;
  }

  int32_t parse_str() {
    int64_t off; int32_t len; uint8_t dec;
    if (!scan_string(&off, &len, &dec)) { ok = false; return -1; }
    int32_t i = push(V_STR);
    nodes[i].str_off = off;
    nodes[i].str_len = len;
    nodes[i].str_decoded = dec;
    return i;
  }

  int32_t parse_arr() {
    ++p;
    int32_t self = push(V_ARR);
    skip_ws();
    if (p < end && *p == ']') { ++p; return self; }
    int32_t prev = -1, count = 0;
    for (;;) {
      int32_t child = parse_value();
      if (!ok) return -1;
      if (prev < 0) nodes[self].first_child = child; else nodes[prev].next_sibling = child;
      prev = child;
      ++count;
      skip_ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; break; }
      ok = false; return -1;
    }
    nodes[self].nchildren = count;
    return self;
  }

  int32_t parse_obj() {
    ++p;
    int32_t self = push(V_OBJ);
    skip_ws();
    if (p < end && *p == '}') { ++p; return self; }
    int32_t prev = -1, count = 0;
    for (;;) {
      skip_ws();
      if (p >= end || *p != '"') { ok = false; return -1; }
      int64_t koff; int32_t klen; uint8_t kdec;
      if (!scan_string(&koff, &klen, &kdec)) { ok = false; return -1; }
      skip_ws();
      if (p >= end || *p != ':') { ok = false; return -1; }
      ++p;
      int32_t child = parse_value();
      if (!ok) return -1;
      nodes[child].key_off = koff;
      nodes[child].key_len = klen;
      nodes[child].key_decoded = kdec;
      if (prev < 0) nodes[self].first_child = child; else nodes[prev].next_sibling = child;
      prev = child;
      ++count;
      skip_ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; break; }
      ok = false; return -1;
    }
    nodes[self].nchildren = count;
    return self;
  }
};

// ---------------------------------------------------------------------------
// rendering (gjson String() semantics, matching compiler/encode.py::_render)
// ---------------------------------------------------------------------------

// Python repr(float) equivalent: shortest round-trip digits, fixed form for
// -4 <= exp10 < 16, else scientific with >=2 exponent digits
void repr_double(double v, std::string& out) {
  if (std::isnan(v)) { out += "nan"; return; }
  if (std::isinf(v)) { out += v > 0 ? "inf" : "-inf"; return; }
  char buf[64];
  auto res = std::to_chars(buf, buf + sizeof buf, v, std::chars_format::scientific);
  // buf: "-d.ddddde±XX" (shortest mantissa)
  char* e = buf;
  while (e < res.ptr && *e != 'e') ++e;
  int exp10 = (int)strtol(e + 1, nullptr, 10);
  std::string mant(buf, e - buf);   // like "-1.2345" or "5"
  bool neg = !mant.empty() && mant[0] == '-';
  if (neg) mant.erase(0, 1);
  std::string digits;
  for (char c : mant) if (c != '.') digits.push_back(c);
  while (digits.size() > 1 && digits.back() == '0') digits.pop_back();
  if (neg) out.push_back('-');
  if (exp10 >= 16 || exp10 < -4) {
    out.push_back(digits[0]);
    if (digits.size() > 1) { out.push_back('.'); out.append(digits, 1, std::string::npos); }
    char eb[16];
    snprintf(eb, sizeof eb, "e%+03d", exp10);
    out += eb;
  } else if (exp10 >= 0) {
    size_t ip = (size_t)exp10 + 1;
    if (digits.size() <= ip) {
      out += digits;
      out.append(ip - digits.size(), '0');
      out += ".0";
    } else {
      out.append(digits, 0, ip);
      out.push_back('.');
      out.append(digits, ip, std::string::npos);
    }
  } else {
    out += "0.";
    out.append((size_t)(-exp10 - 1), '0');
    out += digits;
  }
}

// gjson number String(): int-like floats render as integers
void num_str(double v, std::string& out) {
  if (std::isnan(v) || std::isinf(v)) { repr_double(v, out); return; }
  if (v == std::floor(v) && std::fabs(v) < 1e16) {
    char buf[32];
    auto res = std::to_chars(buf, buf + sizeof buf, (long long)v);
    out.append(buf, res.ptr - buf);
    return;
  }
  repr_double(v, out);
}

void escape_json(const char* s, int32_t n, std::string& out) {
  out.push_back('"');
  for (int32_t i = 0; i < n; ++i) {
    unsigned char c = (unsigned char)s[i];
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out.push_back((char)c);  // ensure_ascii=False: UTF-8 passthrough
        }
    }
  }
  out.push_back('"');
}

// compact raw-JSON dump (json.dumps(v, separators=(",",":"), ensure_ascii=False))
void dump_json(const Doc& d, const Node& n, std::string& out) {
  switch (n.type) {
    case V_NULL: out += "null"; break;
    case V_TRUE: out += "true"; break;
    case V_FALSE: out += "false"; break;
    case V_INT: out.append(d.str(n), n.str_len); break;
    case V_DBL:
      if (std::isnan(n.dbl)) out += "NaN";
      else if (std::isinf(n.dbl)) out += n.dbl > 0 ? "Infinity" : "-Infinity";
      else if (n.dbl == std::floor(n.dbl) && std::fabs(n.dbl) < 1e16) {
        // json.dumps uses repr: 2.0 -> "2.0", -0.0 -> "-0.0"
        if (n.dbl == 0.0 && std::signbit(n.dbl)) out.push_back('-');
        char buf[32];
        auto res = std::to_chars(buf, buf + sizeof buf, (long long)n.dbl);
        out.append(buf, res.ptr - buf);
        out += ".0";
      } else repr_double(n.dbl, out);
      break;
    case V_STR: escape_json(d.str(n), n.str_len, out); break;
    case V_ARR: {
      out.push_back('[');
      bool first = true;
      for (int32_t c = n.first_child; c >= 0; c = (*d.nodes)[c].next_sibling) {
        if (!first) out.push_back(',');
        first = false;
        dump_json(d, (*d.nodes)[c], out);
      }
      out.push_back(']');
      break;
    }
    case V_OBJ: {
      out.push_back('{');
      bool first = true;
      for (int32_t c = n.first_child; c >= 0; c = (*d.nodes)[c].next_sibling) {
        if (!first) out.push_back(',');
        first = false;
        const Node& ch = (*d.nodes)[c];
        escape_json(d.key(ch), ch.key_len, out);
        out.push_back(':');
        dump_json(d, ch, out);
      }
      out.push_back('}');
      break;
    }
  }
}

// render = gjson String() of a resolved value (encode.py::_render)
void render(const Doc& d, int32_t node_idx, std::string& out) {
  if (node_idx < 0) return;  // missing -> ""
  const Node& n = (*d.nodes)[node_idx];
  switch (n.type) {
    case V_NULL: break;      // "" like missing
    case V_TRUE: out += "true"; break;
    case V_FALSE: out += "false"; break;
    case V_INT: out.append(d.str(n), n.str_len); break;
    case V_DBL: num_str(n.dbl, out); break;
    case V_STR: out.append(d.str(n), n.str_len); break;
    default: dump_json(d, n, out); break;
  }
}

// ---------------------------------------------------------------------------
// policy tables
// ---------------------------------------------------------------------------
struct Policy {
  Interner interner;
  std::string strings;                 // owned copy of all table strings
  int32_t n_attrs = 0, n_leaves = 0, n_configs = 0;
  int32_t members_k = 0, dfa_value_bytes = 0, n_byte_attrs = 0;
  std::vector<std::pair<int64_t, int32_t>> seg_views;  // (off,len) into strings
  std::vector<int32_t> attr_seg_offs;   // [n_attrs+1]
  std::vector<uint8_t> attr_complex;    // [n_attrs]
  std::vector<int32_t> attr_byte_slot;  // [n_attrs]
  std::vector<int32_t> leaf_op, leaf_attr, leaf_const;
  std::vector<int32_t> cfg_attr_offs, cfg_attr_idx;
  std::vector<int32_t> cfg_cpu_offs, cfg_cpu_idx;
};

struct Task { int32_t r, leaf; int32_t val_len; std::string val; };
// val_len: >=0 rendered string present; -1 tree-eval in Python; -2 full
// Python fallback for this (doc, leaf)

// walk a plain dot-path; returns node index or -1 (missing)
int32_t walk(const Doc& d, int32_t root, const Policy& p, int32_t attr) {
  int32_t cur = root;
  for (int32_t s = p.attr_seg_offs[attr]; s < p.attr_seg_offs[attr + 1]; ++s) {
    if (cur < 0) return -1;
    const Node& n = (*d.nodes)[cur];
    const char* kp = p.strings.data() + p.seg_views[s].first;
    int32_t klen = p.seg_views[s].second;
    if (n.type == V_OBJ) {
      int32_t found = -1;
      for (int32_t c = n.first_child; c >= 0; c = (*d.nodes)[c].next_sibling) {
        const Node& ch = (*d.nodes)[c];
        if (ch.key_len == klen && memcmp(d.key(ch), kp, (size_t)klen) == 0) { found = c; break; }
      }
      cur = found;
    } else if (n.type == V_ARR) {
      // match encode.py fast resolver: int(k), only non-negative in range;
      // Python int() tolerates surrounding whitespace and a leading sign
      const char* q = kp; const char* qe = kp + klen;
      while (q < qe && (*q == ' ' || *q == '\t')) ++q;
      while (qe > q && (qe[-1] == ' ' || qe[-1] == '\t')) --qe;
      bool neg = false;
      if (q < qe && (*q == '+' || *q == '-')) { neg = (*q == '-'); ++q; }
      if (q == qe) return -1;
      int64_t idx = 0;
      for (; q < qe; ++q) {
        if (*q < '0' || *q > '9') return -1;
        idx = idx * 10 + (*q - '0');
        if (idx > n.nchildren) break;
      }
      if (neg || idx >= n.nchildren) return -1;
      int32_t c = n.first_child;
      for (int64_t i = 0; i < idx; ++i) c = (*d.nodes)[c].next_sibling;
      cur = c;
    } else {
      return -1;
    }
  }
  return cur;
}

struct ThreadScratch {
  std::vector<Node> nodes;
  std::string decode;
  std::vector<int32_t> attr_epoch;
  std::vector<int32_t> attr_node;        // resolved node per attr (epoch-gated)
  std::vector<std::string> attr_rendered;
  std::vector<std::vector<int32_t>> attr_elem_ids;  // full membership ids
  std::vector<Task> tasks;
};

// shared CPU-leaf pass: identical for the JSON-DOM and PyObject front-ends
// (encode.py :205-241 semantics)
inline void process_cpu_leaves(
    const Policy* p, int32_t r, int32_t row,
    const std::vector<int32_t>& attr_epoch,
    const std::vector<std::string>& attr_rendered,
    const std::vector<std::vector<int32_t>>& attr_elem_ids,
    int32_t A, int32_t L, int32_t NB,
    const uint8_t* byte_ovf, const uint8_t* overflow,
    uint8_t* cpu_lane, std::vector<Task>& tasks) {
  for (int32_t li = p->cfg_cpu_offs[row]; li < p->cfg_cpu_offs[row + 1]; ++li) {
    int32_t leaf = p->cfg_cpu_idx[li];
    int32_t op = p->leaf_op[leaf];
    if (op == OP_ERROR) continue;
    if (op == OP_TREE_CPU) {
      tasks.push_back(Task{r, leaf, -1, {}});
      continue;
    }
    int32_t attr = p->leaf_attr[leaf];
    if (p->attr_complex[attr]) {
      tasks.push_back(Task{r, leaf, -2, {}});
      continue;
    }
    bool have = attr_epoch[attr] == r;
    if (op == OP_REGEX_DFA) {
      int32_t slot = p->attr_byte_slot[attr];
      if (slot >= 0 && byte_ovf[(int64_t)r * NB + slot]) {
        std::string v = have ? attr_rendered[attr] : std::string();
        tasks.push_back(Task{r, leaf, (int32_t)v.size(), std::move(v)});
      }
    } else if (op == OP_CPU) {
      std::string v = have ? attr_rendered[attr] : std::string();
      tasks.push_back(Task{r, leaf, (int32_t)v.size(), std::move(v)});
    } else if (op == OP_INCL || op == OP_EXCL) {
      if (overflow[(int64_t)r * A + attr]) {
        bool member = false;
        if (have) {
          for (int32_t eid : attr_elem_ids[attr])
            if (eid == p->leaf_const[leaf]) { member = true; break; }
        }
        cpu_lane[(int64_t)r * L + leaf] = (op == OP_INCL) ? member : !member;
      }
    }
  }
}

// merge per-source task lists into the flat output arrays; returns n_tasks
// or -1 on capacity overflow (caller falls back to the Python encoder)
inline int64_t merge_tasks(
    std::vector<Task>* lists, int n_lists,
    int32_t* task_r, int32_t* task_leaf, int64_t* task_val_off, int32_t* task_val_len,
    int32_t max_tasks, char* task_arena, int64_t arena_cap) {
  int64_t n_tasks = 0, arena_used = 0;
  for (int t = 0; t < n_lists; ++t) {
    for (Task& tk : lists[t]) {
      if (n_tasks >= max_tasks) return -1;
      if (tk.val_len > 0 && arena_used + tk.val_len > arena_cap) return -1;
      task_r[n_tasks] = tk.r;
      task_leaf[n_tasks] = tk.leaf;
      task_val_len[n_tasks] = tk.val_len;
      if (tk.val_len > 0) {
        memcpy(task_arena + arena_used, tk.val.data(), (size_t)tk.val_len);
        task_val_off[n_tasks] = arena_used;
        arena_used += tk.val_len;
      } else {
        task_val_off[n_tasks] = 0;
      }
      ++n_tasks;
    }
  }
  return n_tasks;
}

}  // namespace

extern "C" {

Policy* atpu_policy_new(
    const char* intern_blob, const int64_t* intern_offs, const int32_t* intern_ids, int32_t n_intern,
    int32_t n_attrs,
    const char* seg_blob, const int64_t* seg_offs, int32_t n_segs,
    const int32_t* attr_seg_offs,
    const uint8_t* attr_complex,
    const int32_t* attr_byte_slot,
    int32_t n_leaves,
    const int32_t* leaf_op, const int32_t* leaf_attr, const int32_t* leaf_const,
    int32_t n_configs,
    const int32_t* cfg_attr_offs, const int32_t* cfg_attr_idx,
    const int32_t* cfg_cpu_offs, const int32_t* cfg_cpu_idx,
    int32_t members_k, int32_t dfa_value_bytes, int32_t n_byte_attrs) {
  Policy* p = new Policy();
  // own copies of the intern blob + segment strings so numpy temporaries can die
  int64_t intern_total = intern_offs[n_intern];
  int64_t seg_total = seg_offs[n_segs];
  p->strings.reserve((size_t)(intern_total + seg_total));
  p->strings.append(intern_blob, (size_t)intern_total);
  p->strings.append(seg_blob, (size_t)seg_total);
  {
    std::vector<int64_t> offs(n_intern + 1);
    for (int32_t i = 0; i <= n_intern; ++i) offs[i] = intern_offs[i];
    p->interner.build(p->strings.data(), offs.data(), intern_ids, n_intern);
  }
  p->seg_views.resize(n_segs);
  for (int32_t i = 0; i < n_segs; ++i)
    p->seg_views[i] = {intern_total + seg_offs[i], (int32_t)(seg_offs[i + 1] - seg_offs[i])};
  p->n_attrs = n_attrs;
  p->attr_seg_offs.assign(attr_seg_offs, attr_seg_offs + n_attrs + 1);
  p->attr_complex.assign(attr_complex, attr_complex + n_attrs);
  p->attr_byte_slot.assign(attr_byte_slot, attr_byte_slot + n_attrs);
  p->n_leaves = n_leaves;
  p->leaf_op.assign(leaf_op, leaf_op + n_leaves);
  p->leaf_attr.assign(leaf_attr, leaf_attr + n_leaves);
  p->leaf_const.assign(leaf_const, leaf_const + n_leaves);
  p->n_configs = n_configs;
  p->cfg_attr_offs.assign(cfg_attr_offs, cfg_attr_offs + n_configs + 1);
  p->cfg_attr_idx.assign(cfg_attr_idx, cfg_attr_idx + cfg_attr_offs[n_configs]);
  p->cfg_cpu_offs.assign(cfg_cpu_offs, cfg_cpu_offs + n_configs + 1);
  p->cfg_cpu_idx.assign(cfg_cpu_idx, cfg_cpu_idx + cfg_cpu_offs[n_configs]);
  p->members_k = members_k;
  p->dfa_value_bytes = dfa_value_bytes;
  p->n_byte_attrs = n_byte_attrs;
  return p;
}

void atpu_policy_free(Policy* p) { delete p; }

// id stores go through store_id so the wire buffers can be int16 when the
// interner fits (compiler/pack.py wire_dtype) — halves the dominant tensors
static inline void store_id(void* base, int64_t idx, int32_t v, int elem16) {
  if (elem16) ((int16_t*)base)[idx] = (int16_t)v;
  else ((int32_t*)base)[idx] = v;
}

int64_t atpu_encode(
    const Policy* p,
    const char* json_blob, const int64_t* doc_offs, int32_t n_docs,
    const int32_t* config_rows,
    int32_t A, int32_t K, int32_t L, int32_t NB, int32_t DVB,
    void* attrs_val, void* attrs_members, uint8_t* overflow,
    uint8_t* cpu_lane, uint8_t* attr_bytes, uint8_t* byte_ovf,
    int32_t* task_r, int32_t* task_leaf, int64_t* task_val_off, int32_t* task_val_len,
    int32_t max_tasks, char* task_arena, int64_t arena_cap,
    int32_t n_threads, int32_t elem16) {
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_docs) n_threads = n_docs > 0 ? n_docs : 1;

  std::vector<ThreadScratch> scratch(n_threads);
  std::vector<std::thread> threads;
  std::vector<int8_t> failed(n_threads, 0);

  auto work = [&](int t) {
    ThreadScratch& sc = scratch[t];
    sc.attr_epoch.assign(A, -1);
    sc.attr_node.assign(A, -1);
    sc.attr_rendered.resize(A);
    sc.attr_elem_ids.resize(A);
    int32_t lo = (int32_t)((int64_t)n_docs * t / n_threads);
    int32_t hi = (int32_t)((int64_t)n_docs * (t + 1) / n_threads);
    std::string tmp;
    for (int32_t r = lo; r < hi; ++r) {
      sc.nodes.clear();
      sc.decode.clear();
      const char* dstart = json_blob + doc_offs[r];
      const char* dend = json_blob + doc_offs[r + 1];
      Parser ps{dstart, dend, sc.nodes, sc.decode, json_blob};
      int32_t root = ps.parse_value();
      if (!ps.ok) { failed[t] = 1; return; }
      Doc doc{&sc.nodes, &sc.decode, json_blob};
      int32_t row = config_rows[r];

      // ---- resolve + scatter each attr this config references ----
      for (int32_t ai = p->cfg_attr_offs[row]; ai < p->cfg_attr_offs[row + 1]; ++ai) {
        int32_t attr = p->cfg_attr_idx[ai];
        if (p->attr_complex[attr]) continue;  // finished in Python
        int32_t node = walk(doc, root, *p, attr);
        sc.attr_epoch[attr] = r;
        sc.attr_node[attr] = node;
        std::string& rendered = sc.attr_rendered[attr];
        rendered.clear();
        render(doc, node, rendered);
        int32_t vid = p->interner.lookup(rendered.data(), rendered.size());
        store_id(attrs_val, (int64_t)r * A + attr, vid, elem16);
        int32_t slot = p->attr_byte_slot[attr];
        if (slot >= 0) {
          if ((int64_t)rendered.size() > DVB ||
              memchr(rendered.data(), 0, rendered.size()) != nullptr) {
            byte_ovf[(int64_t)r * NB + slot] = 1;
          } else if (!rendered.empty()) {
            memcpy(attr_bytes + ((int64_t)r * NB + slot) * DVB, rendered.data(), rendered.size());
          }
        }
        // membership (gjson Array() semantics)
        std::vector<int32_t>& elems = sc.attr_elem_ids[attr];
        elems.clear();
        const Node& n = sc.nodes[node < 0 ? 0 : node];
        if (node >= 0 && n.type == V_ARR) {
          int32_t k = 0;
          for (int32_t c = n.first_child; c >= 0; c = sc.nodes[c].next_sibling, ++k) {
            tmp.clear();
            render(doc, c, tmp);
            int32_t eid = p->interner.lookup(tmp.data(), tmp.size());
            elems.push_back(eid);
            if (k < K) store_id(attrs_members, ((int64_t)r * A + attr) * K + k, eid, elem16);
          }
          if ((int32_t)elems.size() > K) overflow[(int64_t)r * A + attr] = 1;
        } else if (node >= 0 && n.type != V_NULL) {
          store_id(attrs_members, ((int64_t)r * A + attr) * K, vid, elem16);
          elems.push_back(vid);
        }
      }

      // ---- CPU-lane leaves ----
      process_cpu_leaves(p, r, row, sc.attr_epoch, sc.attr_rendered,
                         sc.attr_elem_ids, A, L, NB, byte_ovf, overflow,
                         cpu_lane, sc.tasks);
    }
  };

  if (n_threads == 1) {
    work(0);
  } else {
    threads.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) threads.emplace_back(work, t);
    for (auto& th : threads) th.join();
  }
  for (int t = 0; t < n_threads; ++t)
    if (failed[t]) return -2;  // parse failure -> caller falls back

  // ---- merge per-thread task lists ----
  std::vector<std::vector<Task>> lists;
  lists.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) lists.push_back(std::move(scratch[t].tasks));
  return merge_tasks(lists.data(), n_threads, task_r, task_leaf, task_val_off,
                     task_val_len, max_tasks, task_arena, arena_cap);
}

}  // extern "C"
