// Native device-owner gRPC ext_authz frontend.
//
// The reference serves Check() from a Go gRPC server in the same process as
// the evaluation hot loop (ref: main.go:437-488, pkg/service/auth.go:239-310).
// The TPU-era equivalent must keep ONE process owning the chip (TPUs are
// process-exclusive) while the wire path runs at native speed: this file is
// an epoll HTTP/2 gRPC server (framing/HPACK via the system libnghttp2,
// loaded with dlopen so the encoder stays usable without it) that parses
// CheckRequest protobufs, encodes pattern-only ("fast lane") requests
// straight into the packed kernel operands, micro-batches them, and hands
// each batch to the Python device-owner thread for ONE JAX dispatch.  The
// per-request Python cost of the asyncio engine loop (~45µs) drops to zero;
// Python is touched once per batch.
//
// Correctness contract:
//   - fast lane only for configs whose full pipeline semantics reduce to
//     the compiled kernel verdict (anonymous identity + compiled pattern
//     authorization + static responses) — eligibility decided in Python
//     (runtime/native_frontend.py), byte-exact response templates built
//     with the same pb2 code as the Python gRPC server;
//   - everything else (OIDC identities, metadata fetches, templated
//     denyWith, wildcard host corpora, …) routes to the Python pipeline
//     over the slow queue — full semantics, lower throughput;
//   - the packed verdict column 0 is exactly the pipeline's decision for a
//     fast-lane config: ∧ over evaluators of (¬cond ∨ rule)
//     (ops/pattern_eval.py eval_verdicts; ref pkg/service/auth_pipeline.go:287-322).
//
// Compiled as part of the _atpuenc single translation unit (pymod.cpp).

#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/timerfd.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <cctype>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

// ---------------------------------------------------------------------------
// nghttp2 ABI subset (dlopen'd from libnghttp2.so.14; prototypes per the
// public stable C API)
// ---------------------------------------------------------------------------
namespace ng {

typedef struct nghttp2_session nghttp2_session;
typedef struct nghttp2_session_callbacks nghttp2_session_callbacks;
typedef struct nghttp2_option nghttp2_option;

typedef struct {
  size_t length;
  int32_t stream_id;
  uint8_t type;
  uint8_t flags;
  uint8_t reserved;
} nghttp2_frame_hd;

typedef struct {
  uint8_t* name;
  uint8_t* value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
} nghttp2_nv;

typedef union {
  int fd;
  void* ptr;
} nghttp2_data_source;

typedef ssize_t (*nghttp2_data_read_callback)(nghttp2_session*, int32_t,
                                              uint8_t*, size_t, uint32_t*,
                                              nghttp2_data_source*, void*);

typedef struct {
  nghttp2_data_source source;
  nghttp2_data_read_callback read_callback;
} nghttp2_data_provider;

typedef struct {
  int32_t settings_id;
  uint32_t value;
} nghttp2_settings_entry;

enum {
  NGHTTP2_FLAG_END_STREAM = 0x01,
  NGHTTP2_DATA_FLAG_EOF = 0x01,
  NGHTTP2_DATA_FLAG_NO_END_STREAM = 0x02,
  NGHTTP2_SETTINGS_MAX_CONCURRENT_STREAMS = 0x03,
  NGHTTP2_SETTINGS_INITIAL_WINDOW_SIZE = 0x04,
  NGHTTP2_DATA = 0,
  NGHTTP2_HEADERS = 1,
  NGHTTP2_ERR_TEMPORAL_CALLBACK_FAILURE = -521,
};

typedef ssize_t (*send_cb)(nghttp2_session*, const uint8_t*, size_t, int, void*);
typedef int (*frame_recv_cb)(nghttp2_session*, const void*, void*);
typedef int (*data_chunk_cb)(nghttp2_session*, uint8_t, int32_t, const uint8_t*, size_t, void*);
typedef int (*header_cb)(nghttp2_session*, const void*, const uint8_t*, size_t,
                         const uint8_t*, size_t, uint8_t, void*);
typedef int (*stream_close_cb)(nghttp2_session*, int32_t, uint32_t, void*);

struct Api {
  int (*callbacks_new)(nghttp2_session_callbacks**);
  void (*callbacks_del)(nghttp2_session_callbacks*);
  void (*set_on_frame_recv)(nghttp2_session_callbacks*, frame_recv_cb);
  void (*set_on_data_chunk)(nghttp2_session_callbacks*, data_chunk_cb);
  void (*set_on_header)(nghttp2_session_callbacks*, header_cb);
  void (*set_on_stream_close)(nghttp2_session_callbacks*, stream_close_cb);
  int (*session_server_new)(nghttp2_session**, const nghttp2_session_callbacks*, void*);
  void (*session_del)(nghttp2_session*);
  ssize_t (*mem_recv)(nghttp2_session*, const uint8_t*, size_t);
  ssize_t (*mem_send)(nghttp2_session*, const uint8_t**);
  int (*want_read)(nghttp2_session*);
  int (*want_write)(nghttp2_session*);
  int (*submit_response)(nghttp2_session*, int32_t, const nghttp2_nv*, size_t,
                         const nghttp2_data_provider*);
  int (*submit_trailer)(nghttp2_session*, int32_t, const nghttp2_nv*, size_t);
  int (*submit_settings)(nghttp2_session*, uint8_t, const nghttp2_settings_entry*, size_t);
  int (*submit_window_update)(nghttp2_session*, uint8_t, int32_t, int32_t);
  bool ok = false;
};

static Api api;

static bool load() {
  if (api.ok) return true;
  void* h = dlopen("libnghttp2.so.14", RTLD_NOW | RTLD_GLOBAL);
  if (!h) h = dlopen("libnghttp2.so", RTLD_NOW | RTLD_GLOBAL);
  if (!h) return false;
  auto sym = [&](const char* n) { return dlsym(h, n); };
  api.callbacks_new = (int (*)(nghttp2_session_callbacks**))sym("nghttp2_session_callbacks_new");
  api.callbacks_del = (void (*)(nghttp2_session_callbacks*))sym("nghttp2_session_callbacks_del");
  api.set_on_frame_recv = (void (*)(nghttp2_session_callbacks*, frame_recv_cb))sym(
      "nghttp2_session_callbacks_set_on_frame_recv_callback");
  api.set_on_data_chunk = (void (*)(nghttp2_session_callbacks*, data_chunk_cb))sym(
      "nghttp2_session_callbacks_set_on_data_chunk_recv_callback");
  api.set_on_header = (void (*)(nghttp2_session_callbacks*, header_cb))sym(
      "nghttp2_session_callbacks_set_on_header_callback");
  api.set_on_stream_close = (void (*)(nghttp2_session_callbacks*, stream_close_cb))sym(
      "nghttp2_session_callbacks_set_on_stream_close_callback");
  api.session_server_new = (int (*)(nghttp2_session**, const nghttp2_session_callbacks*, void*))sym(
      "nghttp2_session_server_new");
  api.session_del = (void (*)(nghttp2_session*))sym("nghttp2_session_del");
  api.mem_recv = (ssize_t(*)(nghttp2_session*, const uint8_t*, size_t))sym("nghttp2_session_mem_recv");
  api.mem_send = (ssize_t(*)(nghttp2_session*, const uint8_t**))sym("nghttp2_session_mem_send");
  api.want_read = (int (*)(nghttp2_session*))sym("nghttp2_session_want_read");
  api.want_write = (int (*)(nghttp2_session*))sym("nghttp2_session_want_write");
  api.submit_response = (int (*)(nghttp2_session*, int32_t, const nghttp2_nv*, size_t,
                                 const nghttp2_data_provider*))sym("nghttp2_submit_response");
  api.submit_trailer = (int (*)(nghttp2_session*, int32_t, const nghttp2_nv*, size_t))sym(
      "nghttp2_submit_trailer");
  api.submit_settings = (int (*)(nghttp2_session*, uint8_t, const nghttp2_settings_entry*,
                                 size_t))sym("nghttp2_submit_settings");
  api.submit_window_update = (int (*)(nghttp2_session*, uint8_t, int32_t, int32_t))sym(
      "nghttp2_submit_window_update");
  api.ok = api.callbacks_new && api.callbacks_del && api.set_on_frame_recv &&
           api.set_on_data_chunk && api.set_on_header && api.set_on_stream_close &&
           api.session_server_new && api.session_del && api.mem_recv && api.mem_send &&
           api.want_read && api.want_write && api.submit_response && api.submit_trailer &&
           api.submit_settings && api.submit_window_update;
  return api.ok;
}

}  // namespace ng

namespace fe {

// ---------------------------------------------------------------------------
// Minimal protobuf walker for envoy CheckRequest
// (field numbers: protos/src/envoy/service/auth/v3/*.proto)
// ---------------------------------------------------------------------------
struct PbView {
  const char* p = nullptr;
  size_t n = 0;
  bool set = false;
  std::string str() const { return std::string(p ? p : "", n); }
};

struct ReqView {
  bool has_attributes = false, has_request = false, has_http = false;
  PbView method, path, host, scheme, query, fragment, protocol;
  PbView source_cert;  // AttributeContext.source.certificate (Peer field 5)
  int64_t size = 0;
  std::vector<std::pair<PbView, PbView>> headers;   // last-wins on dup keys
  std::vector<std::pair<PbView, PbView>> ctx_ext;
};

static bool pb_varint(const char*& p, const char* end, uint64_t& v) {
  v = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = (uint8_t)*p++;
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
  }
  return false;
}

// returns false on malformed input
static bool pb_skip(const char*& p, const char* end, int wt) {
  uint64_t v;
  switch (wt) {
    case 0: return pb_varint(p, end, v);
    case 1: if (end - p < 8) return false; p += 8; return true;
    case 2:
      if (!pb_varint(p, end, v) || (uint64_t)(end - p) < v) return false;
      p += v; return true;
    case 5: if (end - p < 4) return false; p += 4; return true;
    default: return false;
  }
}

static bool pb_len(const char*& p, const char* end, PbView& out) {
  uint64_t v;
  if (!pb_varint(p, end, v) || (uint64_t)(end - p) < v) return false;
  out.p = p;
  out.n = (size_t)v;
  out.set = true;
  p += v;
  return true;
}

static bool parse_map_entry(PbView msg, PbView& k, PbView& v) {
  const char* p = msg.p;
  const char* end = msg.p + msg.n;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(p, end, tag)) return false;
    int f = (int)(tag >> 3), wt = (int)(tag & 7);
    if (f == 1 && wt == 2) { if (!pb_len(p, end, k)) return false; }
    else if (f == 2 && wt == 2) { if (!pb_len(p, end, v)) return false; }
    else if (!pb_skip(p, end, wt)) return false;
  }
  return true;
}

static bool parse_http(PbView msg, ReqView& rv) {
  const char* p = msg.p;
  const char* end = msg.p + msg.n;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(p, end, tag)) return false;
    int f = (int)(tag >> 3), wt = (int)(tag & 7);
    PbView v;
    switch (f) {
      case 2: if (wt != 2 || !pb_len(p, end, v)) return false; rv.method = v; break;
      case 3: {  // headers map entry
        if (wt != 2 || !pb_len(p, end, v)) return false;
        PbView k, val;
        if (!parse_map_entry(v, k, val)) return false;
        rv.headers.emplace_back(k, val);
        break;
      }
      case 4: if (wt != 2 || !pb_len(p, end, v)) return false; rv.path = v; break;
      case 5: if (wt != 2 || !pb_len(p, end, v)) return false; rv.host = v; break;
      case 6: if (wt != 2 || !pb_len(p, end, v)) return false; rv.scheme = v; break;
      case 7: if (wt != 2 || !pb_len(p, end, v)) return false; rv.query = v; break;
      case 8: if (wt != 2 || !pb_len(p, end, v)) return false; rv.fragment = v; break;
      case 9: {
        uint64_t u;
        if (wt != 0 || !pb_varint(p, end, u)) return false;
        rv.size = (int64_t)u;
        break;
      }
      case 10: if (wt != 2 || !pb_len(p, end, v)) return false; rv.protocol = v; break;
      default: if (!pb_skip(p, end, wt)) return false;
    }
  }
  return true;
}

static bool parse_check_request(const char* data, size_t n, ReqView& rv) {
  const char* p = data;
  const char* end = data + n;
  PbView attrs;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(p, end, tag)) return false;
    int f = (int)(tag >> 3), wt = (int)(tag & 7);
    if (f == 1 && wt == 2) {
      if (!pb_len(p, end, attrs)) return false;
      rv.has_attributes = true;
    } else if (!pb_skip(p, end, wt)) return false;
  }
  if (!attrs.set) return true;
  p = attrs.p;
  end = attrs.p + attrs.n;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(p, end, tag)) return false;
    int f = (int)(tag >> 3), wt = (int)(tag & 7);
    if (f == 1 && wt == 2) {  // source peer (certificate at field 5)
      PbView peer;
      if (!pb_len(p, end, peer)) return false;
      const char* q = peer.p;
      const char* qe = peer.p + peer.n;
      while (q < qe) {
        uint64_t t2;
        if (!pb_varint(q, qe, t2)) return false;
        int f2 = (int)(t2 >> 3), w2 = (int)(t2 & 7);
        if (f2 == 5 && w2 == 2) {
          if (!pb_len(q, qe, rv.source_cert)) return false;
        } else if (!pb_skip(q, qe, w2)) return false;
      }
    } else if (f == 4 && wt == 2) {  // request
      PbView req;
      if (!pb_len(p, end, req)) return false;
      rv.has_request = true;
      const char* q = req.p;
      const char* qe = req.p + req.n;
      while (q < qe) {
        uint64_t t2;
        if (!pb_varint(q, qe, t2)) return false;
        int f2 = (int)(t2 >> 3), w2 = (int)(t2 & 7);
        if (f2 == 2 && w2 == 2) {  // http
          PbView http;
          if (!pb_len(q, qe, http)) return false;
          rv.has_http = true;
          if (!parse_http(http, rv)) return false;
        } else if (!pb_skip(q, qe, w2)) return false;
      }
    } else if (f == 10 && wt == 2) {  // context_extensions entry
      PbView v, k, val;
      if (!pb_len(p, end, v)) return false;
      if (!parse_map_entry(v, k, val)) return false;
      rv.ctx_ext.emplace_back(k, val);
    } else if (!pb_skip(p, end, wt)) return false;
  }
  return true;
}

// last-wins lookup (protobuf map semantics on duplicate keys)
static const PbView* map_get(const std::vector<std::pair<PbView, PbView>>& m,
                             const char* key, size_t klen) {
  const PbView* out = nullptr;
  for (const auto& kv : m)
    if (kv.first.n == klen && memcmp(kv.first.p, key, klen) == 0) out = &kv.second;
  return out;
}

// ---------------------------------------------------------------------------
// Snapshot: everything the fast lane needs, swapped atomically on reconcile
// ---------------------------------------------------------------------------
enum PlanKind {
  K_CONST = 0, K_METHOD, K_PATH, K_URL_PATH, K_QUERY, K_HOST, K_SCHEME,
  K_PROTOCOL, K_SIZE, K_FRAGMENT, K_HEADER, K_CTX_EXT,
};

struct FastPlan {
  int32_t attr;
  int kind;
  std::string key;              // K_HEADER / K_CTX_EXT
  // K_CONST precomputed encoding:
  int32_t const_vid = 0;
  bool const_missing = false;   // missing/null → no member write
  std::vector<int32_t> const_members;
  std::string const_bytes;      // byte-slot payload (raw value bytes)
  bool const_byte_ovf = false;
};

struct VarEnt {
  int32_t idx;                  // var_plans index
  int64_t exp_ns;               // CLOCK_REALTIME expiry; INT64_MAX = static
  int32_t ok_idx = -1;          // var_oks index (per-identity OK response
                                // bytes — response-template configs); -1 =
                                // the config's default OK
  int32_t deny_idx = -1;        // var_denies index (per-identity DENY bytes
                                // — denyWith templates over the identity)
};

// one identity source of a config (multi-identity configs carry several,
// in pipeline priority-then-declaration order — identity is an OR,
// ref pkg/service/auth_pipeline.go:203-258)
struct CredSource {
  int cred_kind = 0;            // 1 auth header, 2 custom header, 3 cookie,
                                // 4 query, 5 client certificate
  std::string cred_key;
  // static (API key): the full key set is known at refresh time — each
  // key's auth.identity.* operands resolved to constant plan variants
  std::unordered_map<std::string, VarEnt> variants;
  std::deque<std::vector<FastPlan>> var_plans;       // deque: stable refs
  std::deque<std::string> var_oks;                   // per-key OK bytes
  std::deque<std::string> var_denies;                // per-key DENY bytes
  // dyn (OIDC/JWT, mTLS): the variant map is a verified-credential cache
  // registered at runtime by the slow lane.  Entries hold their plans by
  // shared_ptr so overwrites and expiry sweeps reclaim memory immediately
  // while a mid-request reader keeps its copy alive without the lock.
  bool dyn = false;
  struct DynVar {
    std::shared_ptr<const std::vector<FastPlan>> plans;
    int64_t exp_ns;
    // per-credential OK / DENY response bytes (response / denyWith
    // templates over the identity); null = the config's defaults
    std::shared_ptr<const std::string> ok;
    std::shared_ptr<const std::string> deny;
  };
  std::unordered_map<std::string, DynVar> dyn_variants;
};

struct FastConfig {
  int32_t row = 0;
  int32_t shard = 0;            // owning mp shard (sharded corpora; else 0)
  bool has_batch = true;        // false → identity-only: decide entirely here
  // hybrid lane: the kernel covers only part of the authorization phase
  // (procedural Rego / SAR / SpiceDB evaluators stay in Python).  A kernel
  // DENY answers immediately — ∧-semantics, any authz failure denies with
  // the same config bytes — while a kernel PASS hands the RAW request to
  // the slow lane for the full pipeline (which re-runs the covered
  // patterns too: correct by construction, and they are kernel-batched
  // there as well)
  bool hybrid = false;
  std::vector<FastPlan> plans;
  bool needs_split = false;     // any K_URL_PATH / K_QUERY plan
  std::string ok_msg, deny_msg; // CheckResponse payloads (pb2-built in Python)
  // identity sources (empty = anonymous).  A request authenticates via the
  // first source whose credential resolves a variant; an extractable dyn
  // credential that misses its cache routes to the slow lane (it may still
  // verify); with no authentication at all the response is the
  // all-sources-failed template for the observed extraction bitmask
  std::vector<CredSource> sources;
  // [2^n_static] UNAUTHENTICATED templates indexed by which STATIC
  // sources' credentials were present (present ⇒ invalid; absent ⇒
  // missing; dyn sources reaching this path are always missing)
  std::vector<std::string> unauth_msgs;
  std::string ns, name;         // per-authconfig metric labels
};

// per-fc cap on runtime-registered variants (attacker-supplied token floods
// must not grow the map unboundedly; beyond the cap new tokens keep being
// served — correctly — by the slow lane)
static const size_t DYN_VARIANT_CAP = 65536;

static inline int64_t now_realtime_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static inline int64_t now_mono_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

// auth_server_authconfig_duration_seconds bucket bounds — EXACTLY
// prometheus_client's default Histogram buckets, so drained counts map 1:1
// onto the same series the Python pipeline observes
// (ref pkg/service/auth_pipeline.go:26-36 records per-request duration
// histograms; the fast lane records them here and Python folds them in)
static const int64_t DUR_BOUNDS_NS[] = {
    5000000LL,    10000000LL,   25000000LL,  50000000LL,  75000000LL,
    100000000LL,  250000000LL,  500000000LL, 750000000LL, 1000000000LL,
    2500000000LL, 5000000000LL, 7500000000LL, 10000000000LL};
static const int N_DUR_BUCKETS = 15;  // 14 bounds + +Inf
// per-fc slot layout in fc_durs: [15 buckets][sum_ns] = 16 u64
static const int DUR_STRIDE = N_DUR_BUCKETS + 1;

// on-box stage bounds (µs-scale: the stages a co-located chip pays —
// queue-wait enq→flush, execute flush→complete, respond complete→submit)
static const int64_t STAGE_BOUNDS_NS[] = {
    10000LL,     25000LL,     50000LL,     100000LL,   250000LL,
    500000LL,    1000000LL,   2500000LL,   5000000LL,  10000000LL,
    25000000LL,  50000000LL,  100000000LL, 250000000LL, 1000000000LL};
static const int N_STAGE_BUCKETS = 16;  // 15 bounds + +Inf

static inline int dur_bucket(int64_t ns) {
  for (int i = 0; i < N_DUR_BUCKETS - 1; ++i)
    if (ns <= DUR_BOUNDS_NS[i]) return i;
  return N_DUR_BUCKETS - 1;
}

static inline int stage_bucket(int64_t ns) {
  for (int i = 0; i < N_STAGE_BUCKETS - 1; ++i)
    if (ns <= STAGE_BOUNDS_NS[i]) return i;
  return N_STAGE_BUCKETS - 1;
}

struct DfaRef { int32_t row; int32_t col; };  // dfa table row, cpu_dense column

struct Entry {
  uint32_t conn_id;
  int32_t stream_id;
  int32_t fc;
  int64_t t_enq;  // CLOCK_MONOTONIC at encode time (stage/duration hists)
  // per-identity OK / DENY response overrides (response + denyWith
  // templates); the _hold fields keep dyn bytes alive until completion
  const std::string* ok_msg = nullptr;
  std::shared_ptr<const std::string> ok_hold;
  const std::string* deny_msg = nullptr;
  std::shared_ptr<const std::string> deny_hold;
  // hybrid configs only: the raw CheckRequest pb, kept so a kernel PASS
  // can hand the request to the slow lane at completion time (the stream
  // buffer is not safely reachable from a dispatch thread)
  std::string raw;
};

struct Slot {
  // sharded corpora carry a leading shard axis: [Bmax, S, ...]; the
  // single-corpus layout is the S=1 special case of the same strides
  char* attrs_val = nullptr;     // [Bmax, S, A] int16/int32
  char* members = nullptr;       // [Bmax, S, M, K] int16/int32
  uint8_t* cpu_dense = nullptr;  // [Bmax, S, C] bool
  int32_t* config_id = nullptr;  // [Bmax] row within the owning shard
  int32_t* shard_of = nullptr;   // [Bmax] owning shard (null for S=1)
  uint8_t* attr_bytes = nullptr; // [Bmax, S, NB, DVB]
  uint8_t* byte_ovf = nullptr;   // [Bmax, S, NB] bool
};

struct Snapshot {
  int64_t id = 0;
  const Interner* interner = nullptr;  // borrowed from Policy (Python-owned)
  int A = 0, M = 0, K = 0, C = 0, NB = 0, DVB = 0;
  int S = 1;  // mp shards (sharded corpora stack per-shard metadata)
  bool elem16 = false;
  std::vector<int32_t> attr_member_slot;  // [S*A] → M row or -1
  std::vector<int32_t> attr_byte_slot_v;  // [S*A] → NB row or -1
  std::vector<std::vector<DfaRef>> attr_dfas;  // [S*A]; rows globalized
  std::vector<uint8_t> dfa_trans;  // [S*R, St, 256]
  std::vector<uint8_t> dfa_accept; // [S*R, St]
  int dfa_S = 0;
  // head-based trace sampling: route every Nth fast-eligible request to
  // the slow lane for full span export (0 = tracing off → all fast).
  // The reference traces every request (ref pkg/service/auth.go:261); the
  // fast lane never touches Python per request, so sampling trades span
  // completeness for keeping the native throughput while observability is on
  int64_t trace_every = 0;
  // host / "*.suffix" wildcard → fc idx, -1 = slow lane
  std::unordered_map<std::string, int32_t> host_map;
  std::vector<FastConfig> fcs;
  // guards every fc's variants/var_plans once dynamic registration starts
  // (epoll thread looks up; the slow lane inserts via fe_add_variant).
  // FastPlan vectors are immutable after publication, so a looked-up
  // pointer stays valid after unlock (deque push_back never moves elements)
  std::mutex var_mu;
  // batch slots (numpy arrays owned by Python until retirement)
  std::vector<Slot> slots;
  std::vector<int> free_slots;
  std::vector<std::vector<Entry>> slot_entries;
  std::vector<int> slot_count;
  int pending_batches = 0;
  // global response templates (pb2-built in Python for byte parity with the
  // Python gRPC server)
  std::string invalid_msg, notfound_msg, health_msg;
  // per-fc direct-decision counters [ok, unauth_missing, unauth_invalid] —
  // decisions that never enter a batch; the Python dispatcher folds them
  // into the pipeline's Prometheus series (fe_drain_fc_counts)
  std::unique_ptr<std::atomic<uint64_t>[]> fc_counts;
  // per-fc request-duration histograms, DUR_STRIDE u64 each (15 prom
  // buckets + sum_ns) — drained into
  // auth_server_authconfig_duration_seconds (fe_drain_durations)
  std::unique_ptr<std::atomic<uint64_t>[]> fc_durs;
  // monotonic flush time of each slot's current batch
  std::vector<int64_t> slot_flush_ns;
};

// ---------------------------------------------------------------------------
// Connections / streams
// ---------------------------------------------------------------------------
enum StreamKind { SK_UNSET = 0, SK_CHECK, SK_HEALTH, SK_OTHER };

struct StreamSt {
  int kind = SK_UNSET;
  bool compressed = false;
  std::string body;
  // response state
  std::string resp;     // full gRPC message payload (5B prefix + pb)
  size_t resp_off = 0;
  bool responded = false;
};

struct Conn {
  int fd = -1;
  uint32_t id = 0;
  ng::nghttp2_session* sess = nullptr;
  std::unordered_map<int32_t, StreamSt> streams;
  std::string outbuf;
  bool want_eout = false;
  bool dead = false;
};

struct Done {
  uint32_t conn_id;
  int32_t stream_id;
  std::string msg;       // CheckResponse payload (no gRPC prefix)
  int grpc_status = 0;   // non-zero → trailers-only error response
  int64_t t_done = 0;    // completion time (respond-stage histogram)
};

struct SlowReq {
  uint64_t id;
  std::string bytes;     // raw CheckRequest pb
};

struct SlowPending {
  uint32_t conn_id;
  int32_t stream_id;
};

// events to Python
enum EvKind { EV_TIMEOUT = 0, EV_BATCH = 1, EV_SNAP_RETIRED = 3, EV_STOPPED = 4 };
struct Event { int kind; int64_t a, b, c; };

struct Server {
  // config
  int port = 0;
  int bound_port = 0;
  bool any_addr = false;  // bind 0.0.0.0 (servers) vs loopback (bench/tests)
  int bmax = 1024;
  int nslots = 8;
  long window_us = 2000;
  size_t slow_cap = 65536;
  std::string health_msg;  // pre-first-swap health reply

  // epoll machinery
  int epfd = -1, listen_fd = -1, evfd = -1, tfd = -1;
  std::thread thr;
  std::atomic<bool> running{false};

  // shared state
  std::mutex mu;
  std::unordered_map<uint32_t, Conn*> conns;
  uint32_t next_conn_id = 1;
  std::shared_ptr<Snapshot> cur;                      // swapped under mu
  std::unordered_map<int64_t, std::shared_ptr<Snapshot>> snaps;
  // snapshot the epoll thread is mid-request on (under mu): retirement
  // must skip it so direct-decision counter bumps are never lost to an
  // already-drained, erased snapshot
  Snapshot* epoll_pin = nullptr;
  // current filling batch (epoll thread only, but slot recycle under mu)
  int fill_slot = -1;
  int fill_count = 0;
  std::shared_ptr<Snapshot> fill_snap;
  bool timer_armed = false;

  // queues
  std::mutex done_mu;   // done_q only — its own lock so completion storms
                        // from dispatch/slow threads don't contend with
                        // everything else S->mu guards
  std::deque<Done> done_q;                            // under done_mu; evfd wakes epoll
  std::mutex batch_mu;
  std::condition_variable batch_cv;
  std::deque<Event> batch_events;
  std::mutex slow_mu;
  std::condition_variable slow_cv;
  std::deque<SlowReq> slow_q;
  bool stopping = false;
  std::unordered_map<uint64_t, SlowPending> slow_pending;  // under mu
  uint64_t next_slow_id = 1;

  // stats
  std::atomic<uint64_t> n_fast{0}, n_slow{0}, n_notfound{0}, n_invalid{0},
      n_health{0}, n_allowed{0}, n_denied{0}, n_dfa_ovf{0}, n_slow_shed{0},
      n_hybrid{0},
      n_parse_err{0}, n_conns{0}, n_unauth{0}, n_direct_ok{0}, n_dyn_hit{0},
      n_dyn_miss{0}, n_dyn_add{0}, n_trace_sampled{0};
  std::atomic<uint64_t> trace_ctr{0};
  // on-box stage histograms (server-wide): queue-wait (encode→flush),
  // execute (flush→complete_batch), respond (complete→HTTP/2 submit)
  std::atomic<uint64_t> stage_wait[N_STAGE_BUCKETS] = {};
  std::atomic<uint64_t> stage_exec[N_STAGE_BUCKETS] = {};
  std::atomic<uint64_t> stage_respond[N_STAGE_BUCKETS] = {};
  // duration-histogram leftovers of retired snapshots (key ns+'\x1f'+name;
  // under mu)
  std::unordered_map<std::string, std::array<uint64_t, DUR_STRIDE>> dur_leftover;
  // fc counters of retired snapshots not yet drained (key ns+'\x1f'+name;
  // under mu)
  std::unordered_map<std::string, std::array<uint64_t, 3>> fc_leftover;
};

static Server* g_srv = nullptr;

// ---- response submission (epoll thread only) ------------------------------

static ssize_t resp_read_cb(ng::nghttp2_session*, int32_t stream_id, uint8_t* buf,
                            size_t length, uint32_t* data_flags,
                            ng::nghttp2_data_source* source, void*) {
  Conn* c = (Conn*)source->ptr;
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) return ng::NGHTTP2_ERR_TEMPORAL_CALLBACK_FAILURE;
  StreamSt& st = it->second;
  size_t left = st.resp.size() - st.resp_off;
  size_t n = left < length ? left : length;
  memcpy(buf, st.resp.data() + st.resp_off, n);
  st.resp_off += n;
  if (st.resp_off == st.resp.size()) {
    *data_flags = ng::NGHTTP2_DATA_FLAG_EOF | ng::NGHTTP2_DATA_FLAG_NO_END_STREAM;
    static const char kStatus[] = "grpc-status";
    static const char kZero[] = "0";
    ng::nghttp2_nv trailer = {(uint8_t*)kStatus, (uint8_t*)kZero,
                              sizeof(kStatus) - 1, sizeof(kZero) - 1, 0};
    ng::api.submit_trailer(c->sess, stream_id, &trailer, 1);
  }
  return (ssize_t)n;
}

static void nv_set(ng::nghttp2_nv& nv, const char* n, size_t nl, const char* v, size_t vl) {
  nv.name = (uint8_t*)n; nv.namelen = nl;
  nv.value = (uint8_t*)v; nv.valuelen = vl;
  nv.flags = 0;
}

// msg: CheckResponse payload; builds 5-byte gRPC prefix + body, then
// HEADERS(:status 200) + DATA + trailers(grpc-status 0)
static void submit_grpc_response(Conn* c, int32_t stream_id, const std::string& msg) {
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) return;
  StreamSt& st = it->second;
  if (st.responded) return;
  st.responded = true;
  st.resp.clear();
  st.resp.reserve(5 + msg.size());
  uint32_t len = (uint32_t)msg.size();
  char pfx[5] = {0, (char)(len >> 24), (char)(len >> 16), (char)(len >> 8), (char)len};
  st.resp.append(pfx, 5);
  st.resp.append(msg);
  st.resp_off = 0;
  ng::nghttp2_nv nv[2];
  nv_set(nv[0], ":status", 7, "200", 3);
  nv_set(nv[1], "content-type", 12, "application/grpc", 16);
  ng::nghttp2_data_provider dp;
  dp.source.ptr = c;
  dp.read_callback = resp_read_cb;
  ng::api.submit_response(c->sess, stream_id, nv, 2, &dp);
}

// trailers-only gRPC error (no message body)
static void submit_grpc_error(Conn* c, int32_t stream_id, int code) {
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) return;
  if (it->second.responded) return;
  it->second.responded = true;
  char buf[8];
  int n = snprintf(buf, sizeof buf, "%d", code);
  ng::nghttp2_nv nv[3];
  nv_set(nv[0], ":status", 7, "200", 3);
  nv_set(nv[1], "content-type", 12, "application/grpc", 16);
  nv_set(nv[2], "grpc-status", 11, buf, (size_t)n);
  ng::api.submit_response(c->sess, stream_id, nv, 3, nullptr);
}

// ---- fast-lane encode -----------------------------------------------------

static inline void put_id(Snapshot* s, char* base, int64_t idx, int32_t v) {
  if (s->elem16) ((int16_t*)base)[idx] = (int16_t)v;
  else ((int32_t*)base)[idx] = v;
}

// run one DFA over arbitrary-length bytes (exact overflow handling for the
// device regex lane: the value doesn't fit the byte tensor, but the DFA
// itself is length-agnostic — same tables, host scan)
static bool dfa_scan(Snapshot* s, int32_t row, const char* p, size_t n) {
  const uint8_t* t = s->dfa_trans.data() + (size_t)row * s->dfa_S * 256;
  uint8_t state = 0;
  for (size_t i = 0; i < n; ++i) state = t[(size_t)state * 256 + (uint8_t)p[i]];
  return s->dfa_accept[(size_t)row * s->dfa_S + state] != 0;
}

static void render_i64(int64_t v, std::string& out) {
  char buf[24];
  int n = snprintf(buf, sizeof buf, "%lld", (long long)v);
  out.assign(buf, (size_t)n);
}

// mirror of evaluators/credentials.py AuthCredentials.extract
// (ref pkg/auth/credentials.go:62-75); false → credential not found
static bool extract_cred(const CredSource& fc, const ReqView& rv, std::string& cred) {
  const size_t kl = fc.cred_key.size();
  switch (fc.cred_kind) {
    case 1: {  // authorization header: "<key_selector> <cred>"
      const PbView* h = map_get(rv.headers, "authorization", 13);
      if (!h) return false;
      if (h->n < kl + 1 || memcmp(h->p, fc.cred_key.data(), kl) != 0 ||
          h->p[kl] != ' ')
        return false;
      cred.assign(h->p + kl + 1, h->n - kl - 1);
      return true;
    }
    case 2: {  // custom header (name pre-lowercased in Python)
      const PbView* h = map_get(rv.headers, fc.cred_key.data(), kl);
      if (!h) return false;
      cred.assign(h->p, h->n);
      return true;
    }
    case 3: {  // cookie: split on ';', strip, "<key>=<cred>"
      const PbView* h = map_get(rv.headers, "cookie", 6);
      if (!h) return false;
      const char* p = h->p;
      const char* end = p + h->n;
      while (p < end) {
        const char* semi = (const char*)memchr(p, ';', (size_t)(end - p));
        const char* pe = semi ? semi : end;
        const char* a = p;
        const char* b = pe;
        while (a < b && isspace((unsigned char)*a)) ++a;
        while (b > a && isspace((unsigned char)b[-1])) --b;
        if ((size_t)(b - a) >= kl + 1 && memcmp(a, fc.cred_key.data(), kl) == 0 &&
            a[kl] == '=') {
          cred.assign(a + kl + 1, (size_t)(b - a) - kl - 1);
          return true;
        }
        if (!semi) break;
        p = semi + 1;
      }
      return false;
    }
    case 5: {  // client certificate (mTLS): the raw forwarded PEM is the key
      if (!rv.source_cert.set || rv.source_cert.n == 0) return false;
      cred.assign(rv.source_cert.p, rv.source_cert.n);
      return true;
    }
    case 4: {  // query param in the raw path: [?&]<key>=([^&]*)
      if (!rv.path.set) return false;
      const char* p = rv.path.p;
      const size_t n = rv.path.n;
      for (size_t i = 0; i + kl + 2 <= n; ++i) {
        if ((p[i] == '?' || p[i] == '&') &&
            memcmp(p + i + 1, fc.cred_key.data(), kl) == 0 && p[i + 1 + kl] == '=') {
          const char* vs = p + i + 2 + kl;
          const char* ve = (const char*)memchr(vs, '&', (size_t)(p + n - vs));
          cred.assign(vs, ve ? (size_t)(ve - vs) : (size_t)(p + n - vs));
          return true;
        }
      }
      return false;
    }
  }
  return false;
}

// encode one request into row b of the filling slot; returns false when the
// request needs the slow lane after all (odd path shapes).  `extra` carries
// the per-credential K_CONST plan variant (API-key identity), if any.
static bool encode_fast(Server* S, Snapshot* snap, Slot& sl, int b,
                        const FastConfig& fc, const std::vector<FastPlan>* extra,
                        const ReqView& rv) {
  // pre-split path once if any plan needs url_path/query (urlsplit parity
  // only holds for origin-form paths; anything else → slow lane)
  PbView url_path, qpart;
  if (fc.needs_split) {
    if (!rv.path.set || rv.path.n == 0 || rv.path.p[0] != '/') return false;
    const char* p = rv.path.p;
    const char* end = p + rv.path.n;
    const char* q = (const char*)memchr(p, '?', rv.path.n);
    const char* h = (const char*)memchr(p, '#', rv.path.n);
    const char* path_end = end;
    if (h && (!q || h < q)) { path_end = h; q = nullptr; }
    else if (q) path_end = q;
    if (q) {
      const char* qe = h ? h : end;
      qpart.p = q + 1; qpart.n = (size_t)(qe - q - 1); qpart.set = true;
    }
    url_path.p = p; url_path.n = (size_t)(path_end - p); url_path.set = true;
  }

  const int A = snap->A, K = snap->K, NB = snap->NB, DVB = snap->DVB;
  // the request's row along the flattened [B, S] axis: all writes land in
  // its owning shard's slice (other shards keep the zeroed EMPTY encoding)
  const int64_t bs = (int64_t)b * snap->S + fc.shard;
  const int64_t meta0 = (int64_t)fc.shard * A;  // per-shard metadata base
  std::string tmp;
  const std::vector<FastPlan>* lists[2] = {&fc.plans, extra};
  for (int li = 0; li < 2; ++li) {
  if (lists[li] == nullptr) continue;
  for (const FastPlan& pl : *lists[li]) {
    const int32_t attr = pl.attr;
    int32_t vid;
    const char* vp = nullptr;
    size_t vn = 0;
    bool missing = false;
    if (pl.kind == K_CONST) {
      vid = pl.const_vid;
      missing = pl.const_missing;
      vp = pl.const_bytes.data(); vn = pl.const_bytes.size();
    } else {
      switch (pl.kind) {
        case K_METHOD:   vp = rv.method.p;   vn = rv.method.n; break;
        case K_PATH:     vp = rv.path.p;     vn = rv.path.n; break;
        case K_HOST:     vp = rv.host.p;     vn = rv.host.n; break;
        case K_SCHEME:   vp = rv.scheme.p;   vn = rv.scheme.n; break;
        case K_PROTOCOL: vp = rv.protocol.p; vn = rv.protocol.n; break;
        case K_FRAGMENT: vp = rv.fragment.p; vn = rv.fragment.n; break;
        case K_URL_PATH: vp = url_path.p;    vn = url_path.n; break;
        case K_QUERY:
          // wellknown: split.query or http.query
          if (qpart.set && qpart.n) { vp = qpart.p; vn = qpart.n; }
          else { vp = rv.query.p; vn = rv.query.n; }
          break;
        case K_SIZE:
          render_i64(rv.size, tmp);
          vp = tmp.data(); vn = tmp.size();
          break;
        case K_HEADER: {
          const PbView* h = map_get(rv.headers, pl.key.data(), pl.key.size());
          if (h) { vp = h->p; vn = h->n; } else missing = true;
          break;
        }
        case K_CTX_EXT: {
          const PbView* h = map_get(rv.ctx_ext, pl.key.data(), pl.key.size());
          if (h) { vp = h->p; vn = h->n; } else missing = true;
          break;
        }
        default: return false;
      }
      if (vp == nullptr) vn = 0;
      vid = missing ? snap->interner->lookup("", 0) : snap->interner->lookup(vp, vn);
    }
    put_id(snap, sl.attrs_val, bs * A + attr, vid);
    int32_t mslot = snap->attr_member_slot[meta0 + attr];
    if (mslot >= 0) {
      if (pl.kind == K_CONST) {
        for (size_t k = 0; k < pl.const_members.size() && (int)k < K; ++k)
          put_id(snap, sl.members, (bs * snap->M + mslot) * K + k,
                 pl.const_members[k]);
      } else if (!missing) {
        put_id(snap, sl.members, (bs * snap->M + mslot) * K, vid);
      }
    }
    int32_t bslot = snap->attr_byte_slot_v[meta0 + attr];
    if (bslot >= 0) {
      if (pl.kind != K_CONST && vn && memchr(vp, 0, vn) != nullptr)
        return false;  // NUL: byte 0 is the DFA pad identity — Python regex
                       // lane is the only exact evaluator (slow lane)
      bool ovf = pl.kind == K_CONST ? pl.const_byte_ovf : (int)vn > DVB;
      if (ovf) {
        sl.byte_ovf[bs * NB + bslot] = 1;
        S->n_dfa_ovf.fetch_add(1, std::memory_order_relaxed);
        // exact host evaluation of every DFA leaf reading this attr (the
        // DFA is length-agnostic; only the device tensor is fixed-width)
        const char* sp = missing ? "" : vp;
        size_t sn = missing ? 0 : vn;
        for (const DfaRef& d : snap->attr_dfas[meta0 + attr])
          sl.cpu_dense[bs * snap->C + d.col] = dfa_scan(snap, d.row, sp, sn) ? 1 : 0;
      } else if (vn) {
        memcpy(sl.attr_bytes + (bs * NB + bslot) * DVB, vp, vn);
      }
    }
  }
  }
  sl.config_id[b] = fc.row;
  if (sl.shard_of) sl.shard_of[b] = fc.shard;
  return true;
}

// zero row b of the filling slot (arrays may hold a previous batch's rows);
// zeroes ALL S shard slices — non-owning shards must present the EMPTY
// encoding so their verdict contributions stay masked out
static void zero_row(Snapshot* snap, Slot& sl, int b) {
  const int A = snap->A * snap->S, M = snap->M, K = snap->K,
            C = snap->C * snap->S, NB = snap->NB * snap->S,
            DVB = snap->DVB;
  const int MK = M * K * snap->S;
  const int es = snap->elem16 ? 2 : 4;
  // attrs_val ← EMPTY_ID (0), members ← PAD (-3)
  memset(sl.attrs_val + (int64_t)b * A * es, 0, (size_t)A * es);
  if (snap->elem16) {
    int16_t* m = (int16_t*)sl.members + (int64_t)b * MK;
    for (int i = 0; i < MK; ++i) m[i] = -3;
  } else {
    int32_t* m = (int32_t*)sl.members + (int64_t)b * MK;
    for (int i = 0; i < MK; ++i) m[i] = -3;
  }
  memset(sl.cpu_dense + (int64_t)b * C, 0, (size_t)C);
  if (sl.attr_bytes) memset(sl.attr_bytes + (int64_t)b * NB * DVB, 0, (size_t)NB * DVB);
  if (sl.byte_ovf) memset(sl.byte_ovf + (int64_t)b * NB, 0, (size_t)NB);
  if (sl.shard_of) sl.shard_of[b] = 0;
}

// ---- batching (epoll thread) ----------------------------------------------

static void arm_timer(Server* S) {
  struct itimerspec its;
  memset(&its, 0, sizeof its);
  its.it_value.tv_sec = S->window_us / 1000000;
  its.it_value.tv_nsec = (S->window_us % 1000000) * 1000;
  timerfd_settime(S->tfd, 0, &its, nullptr);
  S->timer_armed = true;
}

static void disarm_timer(Server* S) {
  struct itimerspec its;
  memset(&its, 0, sizeof its);
  timerfd_settime(S->tfd, 0, &its, nullptr);
  S->timer_armed = false;
}

static void maybe_retire_locked(Server* S, std::vector<int64_t>& retired);
static void emit_retired(Server* S, const std::vector<int64_t>& retired);

static void flush_batch(Server* S, bool from_timer = false) {
  if (S->fill_slot < 0) {
    disarm_timer(S);
    return;
  }
  std::shared_ptr<Snapshot> snap = S->fill_snap;
  int slot = S->fill_slot, count = S->fill_count;
  std::vector<int64_t> retired;
  bool flushed = false;
  {
    // fill_slot/fill_snap transitions stay under mu: Python threads read
    // fill_snap in maybe_retire_locked (an unsynchronized shared_ptr
    // write would be a data race)
    std::lock_guard<std::mutex> lk(S->mu);
    if (count == 0) {
      // empty held slot (a swap raced a failed encode): return it so the
      // old snapshot can retire
      snap->free_slots.push_back(slot);
      S->fill_slot = -1;
      S->fill_snap.reset();
      maybe_retire_locked(S, retired);
    } else if (from_timer && count < S->bmax && snap->pending_batches >= 6 &&
               snap == S->cur) {
      // saturated: enough batches already hide the device RTT, and a
      // partial flush would burn a whole slot on a part-filled batch —
      // slot capacity in *requests* collapses and fast traffic spills to
      // the slow lane.  Let the batch keep filling; re-check next window.
    } else {
      snap->slot_count[slot] = count;
      snap->slot_flush_ns[slot] = now_mono_ns();
      snap->pending_batches++;
      S->fill_slot = -1;
      S->fill_count = 0;
      S->fill_snap.reset();
      flushed = true;
    }
  }
  emit_retired(S, retired);
  if (flushed || count == 0) {
    disarm_timer(S);
  } else {
    arm_timer(S);  // deferred partial batch: re-check next window
  }
  if (flushed) {
    {
      std::lock_guard<std::mutex> lk(S->batch_mu);
      S->batch_events.push_back({EV_BATCH, snap->id, slot, count});
    }
    S->batch_cv.notify_all();
  }
}

// acquire the filling slot for the current snapshot; nullptr when exhausted
// (back-pressure: request stays queued at the socket)
static Slot* ensure_fill(Server* S, std::shared_ptr<Snapshot>& snap_out) {
  std::lock_guard<std::mutex> lk(S->mu);
  std::shared_ptr<Snapshot> cur = S->cur;
  if (!cur || cur->slots.empty()) return nullptr;
  if (S->fill_slot >= 0 && S->fill_snap != cur) {
    // snapshot changed mid-fill: flush the old batch first (outside mu —
    // just mark and let caller retry)
    return nullptr;
  }
  if (S->fill_slot < 0) {
    if (cur->free_slots.empty()) return nullptr;
    S->fill_slot = cur->free_slots.back();
    cur->free_slots.pop_back();
    S->fill_snap = cur;
    S->fill_count = 0;
    cur->slot_entries[S->fill_slot].clear();
  }
  snap_out = S->fill_snap;
  return &snap_out->slots[S->fill_slot];
}

// ---- request processing (epoll thread) ------------------------------------

// Host resolution with wildcard walk-up (ref pkg/index/index.go:153-174;
// mirrors index/index.py::_get_node): exact hit first, then "*."-prefixed
// suffixes deepest-first — "*.example.com" matches a.example.com,
// b.a.example.com AND example.com itself — then a bare "*".
static bool resolve_host(Snapshot* snap, const std::string& host, int32_t& out) {
  auto it = snap->host_map.find(host);
  if (it != snap->host_map.end()) { out = it->second; return true; }
  size_t pos = 0;
  std::string cand;
  for (;;) {
    cand.assign("*.");
    cand.append(host, pos, std::string::npos);
    auto w = snap->host_map.find(cand);
    if (w != snap->host_map.end()) { out = w->second; return true; }
    size_t dot = host.find('.', pos);
    if (dot == std::string::npos) break;
    pos = dot + 1;
  }
  auto b = snap->host_map.find("*");
  if (b != snap->host_map.end()) { out = b->second; return true; }
  return false;
}

static void push_slow(Server* S, Conn* c, int32_t stream_id, const char* msg, size_t n) {
  uint64_t id;
  bool shed = false;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    if (S->slow_pending.size() >= S->slow_cap) {
      shed = true;
    } else {
      id = S->next_slow_id++;
      S->slow_pending[id] = {c->id, stream_id};
    }
  }
  if (shed) {
    S->n_slow_shed.fetch_add(1, std::memory_order_relaxed);
    submit_grpc_error(c, stream_id, 8);  // RESOURCE_EXHAUSTED
    return;
  }
  {
    std::lock_guard<std::mutex> lk(S->slow_mu);
    S->slow_q.push_back({id, std::string(msg, n)});
  }
  S->slow_cv.notify_all();
  S->n_slow.fetch_add(1, std::memory_order_relaxed);
}

// record one direct (never-batched) decision's duration for fc_idx
static inline void record_direct_dur(Snapshot* snap, int32_t fc_idx, int64_t t0) {
  if (!snap->fc_durs) return;
  int64_t dur = now_mono_ns() - t0;
  auto* d = &snap->fc_durs[(size_t)fc_idx * DUR_STRIDE];
  d[dur_bucket(dur)].fetch_add(1, std::memory_order_relaxed);
  d[N_DUR_BUCKETS].fetch_add((uint64_t)dur, std::memory_order_relaxed);
}

static void process_check(Server* S, Conn* c, int32_t stream_id, StreamSt& st) {
  const int64_t t_start = now_mono_ns();
  if (st.body.size() < 5) { submit_grpc_error(c, stream_id, 13); return; }
  if (st.body[0] != 0) { submit_grpc_error(c, stream_id, 12); return; }  // compressed
  uint32_t mlen = ((uint8_t)st.body[1] << 24) | ((uint8_t)st.body[2] << 16) |
                  ((uint8_t)st.body[3] << 8) | (uint8_t)st.body[4];
  if (st.body.size() < 5 + (size_t)mlen) { submit_grpc_error(c, stream_id, 13); return; }
  const char* msg = st.body.data() + 5;

  std::shared_ptr<Snapshot> snap;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    snap = S->cur;
    S->epoll_pin = snap.get();
  }
  // unpin at every exit; a swap may have been waiting on the pin, so run
  // the retire check the moment it clears
  struct PinGuard {
    Server* S;
    ~PinGuard() {
      std::vector<int64_t> retired;
      {
        std::lock_guard<std::mutex> lk(S->mu);
        S->epoll_pin = nullptr;
        maybe_retire_locked(S, retired);
      }
      emit_retired(S, retired);
    }
  } pin_guard{S};
  if (!snap) { push_slow(S, c, stream_id, msg, mlen); return; }

  ReqView rv;
  if (!parse_check_request(msg, mlen, rv)) {
    S->n_parse_err.fetch_add(1, std::memory_order_relaxed);
    submit_grpc_error(c, stream_id, 13);
    return;
  }
  if (!rv.has_attributes || !rv.has_request || !rv.has_http) {
    S->n_invalid.fetch_add(1, std::memory_order_relaxed);
    submit_grpc_response(c, stream_id, snap->invalid_msg);
    return;
  }
  // host: context_extensions["host"] override, then :authority, then
  // port-strip retry (ref pkg/service/auth.go:270-289)
  const PbView* ov = map_get(rv.ctx_ext, "host", 4);
  std::string host = ov ? ov->str() : rv.host.str();
  int32_t fc_idx;
  bool found = resolve_host(snap.get(), host, fc_idx);
  if (!found) {
    size_t colon = host.rfind(':');
    if (colon != std::string::npos)
      found = resolve_host(snap.get(), host.substr(0, colon), fc_idx);
  }
  if (!found) {
    S->n_notfound.fetch_add(1, std::memory_order_relaxed);
    submit_grpc_response(c, stream_id, snap->notfound_msg);
    return;
  }
  if (fc_idx < 0) { push_slow(S, c, stream_id, msg, mlen); return; }
  if (snap->trace_every > 0 &&
      (int64_t)(S->trace_ctr.fetch_add(1, std::memory_order_relaxed) %
                (uint64_t)snap->trace_every) == 0) {
    // sampled: full pipeline + span export in Python
    S->n_trace_sampled.fetch_add(1, std::memory_order_relaxed);
    push_slow(S, c, stream_id, msg, mlen);
    return;
  }

  FastConfig& fc = snap->fcs[fc_idx];
  const std::vector<FastPlan>* extra = nullptr;
  // keeps a dyn variant's plan vector alive across encode_fast after the
  // variant lock is released (overwrites/sweeps may drop the map entry)
  std::shared_ptr<const std::vector<FastPlan>> dyn_hold;
  // the winning identity's OK/DENY response overrides (template configs)
  const std::string* ok_override = nullptr;
  std::shared_ptr<const std::string> ok_hold;
  const std::string* deny_override = nullptr;
  std::shared_ptr<const std::string> deny_hold;
  if (!fc.sources.empty()) {
    // identity is an OR over the sources, tried in the pipeline's
    // priority-then-declaration order: the first source whose credential
    // resolves a variant authenticates (its auth.* constants ride along).
    // An extractable dyn credential that misses its cache routes to the
    // slow lane — it may still verify there; a missed STATIC credential
    // (unknown API key) just falls through to the next source.  With no
    // authentication at all, the all-fail template for the observed
    // static-extraction bitmask answers (every per-source failure message
    // is a static string in that case, so the aggregate is too —
    // ref pkg/service/auth_pipeline.go:203-258 + :468-472).
    bool authenticated = false;
    uint32_t extracted_static = 0;
    int static_idx = 0;
    std::string cred;
    for (const CredSource& src : fc.sources) {
      const int bit = src.dyn ? -1 : static_idx++;
      cred.clear();
      if (!extract_cred(src, rv, cred)) continue;
      if (src.dyn) {
        {
          std::lock_guard<std::mutex> vlk(snap->var_mu);
          auto vit = src.dyn_variants.find(cred);
          if (vit != src.dyn_variants.end() &&
              vit->second.exp_ns > now_realtime_ns()) {
            dyn_hold = vit->second.plans;
            extra = dyn_hold.get();
            if (vit->second.ok) {
              ok_hold = vit->second.ok;
              ok_override = ok_hold.get();
            }
            if (vit->second.deny) {
              deny_hold = vit->second.deny;
              deny_override = deny_hold.get();
            }
          }
        }
        if (extra == nullptr) {
          // unknown/expired credential: the slow lane verifies (and
          // registers on success) — full pipeline semantics
          S->n_dyn_miss.fetch_add(1, std::memory_order_relaxed);
          push_slow(S, c, stream_id, msg, mlen);
          return;
        }
        S->n_dyn_hit.fetch_add(1, std::memory_order_relaxed);
        authenticated = true;
        break;
      }
      extracted_static |= 1u << bit;
      auto vit = src.variants.find(cred);
      if (vit != src.variants.end()) {
        extra = &src.var_plans[vit->second.idx];
        if (vit->second.ok_idx >= 0)
          ok_override = &src.var_oks[vit->second.ok_idx];
        if (vit->second.deny_idx >= 0)
          deny_override = &src.var_denies[vit->second.deny_idx];
        authenticated = true;
        break;
      }
    }
    if (!authenticated) {
      const bool any_present = extracted_static != 0;
      snap->fc_counts[3 * (size_t)fc_idx + (any_present ? 2 : 1)].fetch_add(
          1, std::memory_order_relaxed);
      S->n_fast.fetch_add(1, std::memory_order_relaxed);
      S->n_unauth.fetch_add(1, std::memory_order_relaxed);
      S->n_denied.fetch_add(1, std::memory_order_relaxed);
      record_direct_dur(snap.get(), fc_idx, t_start);
      submit_grpc_response(c, stream_id, fc.unauth_msgs[extracted_static]);
      return;
    }
  }
  if (!fc.has_batch) {
    // identity-only config: authenticated → OK, no kernel involvement
    snap->fc_counts[3 * (size_t)fc_idx].fetch_add(1, std::memory_order_relaxed);
    S->n_fast.fetch_add(1, std::memory_order_relaxed);
    S->n_direct_ok.fetch_add(1, std::memory_order_relaxed);
    S->n_allowed.fetch_add(1, std::memory_order_relaxed);
    record_direct_dur(snap.get(), fc_idx, t_start);
    submit_grpc_response(c, stream_id,
                         ok_override ? *ok_override : fc.ok_msg);
    return;
  }
  std::shared_ptr<Snapshot> fsnap;
  Slot* sl = ensure_fill(S, fsnap);
  if (sl == nullptr) {
    // no slot (exhausted or snapshot raced): flush and retry once
    flush_batch(S);
    sl = ensure_fill(S, fsnap);
    if (sl == nullptr) { push_slow(S, c, stream_id, msg, mlen); return; }
  }
  if (fsnap != snap) {
    // snapshot swapped between lookup and slot acquire: redo via slow lane
    push_slow(S, c, stream_id, msg, mlen);
    return;
  }
  int b = S->fill_count;
  zero_row(snap.get(), *sl, b);
  if (!encode_fast(S, snap.get(), *sl, b, fc, extra, rv)) {
    push_slow(S, c, stream_id, msg, mlen);
    return;
  }
  snap->slot_entries[S->fill_slot].push_back(
      {c->id, stream_id, fc_idx, t_start, ok_override, std::move(ok_hold),
       deny_override, std::move(deny_hold),
       fc.hybrid ? std::string(msg, mlen) : std::string()});
  S->fill_count++;
  S->n_fast.fetch_add(1, std::memory_order_relaxed);
  if (S->fill_count >= S->bmax) flush_batch(S);
  else if (S->fill_count == 1) arm_timer(S);
}

static void process_request(Server* S, Conn* c, int32_t stream_id) {
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) return;
  StreamSt& st = it->second;
  switch (st.kind) {
    case SK_HEALTH: {
      std::shared_ptr<Snapshot> snap;
      {
        std::lock_guard<std::mutex> lk(S->mu);
        snap = S->cur;
      }
      S->n_health.fetch_add(1, std::memory_order_relaxed);
      submit_grpc_response(c, stream_id, snap ? snap->health_msg : S->health_msg);
      break;
    }
    case SK_CHECK:
      if (st.compressed) { submit_grpc_error(c, stream_id, 12); break; }
      process_check(S, c, stream_id, st);
      break;
    default:
      submit_grpc_error(c, stream_id, 12);  // UNIMPLEMENTED
  }
}

// ---- nghttp2 callbacks ----------------------------------------------------

static int on_header(ng::nghttp2_session*, const void* frame, const uint8_t* name,
                     size_t namelen, const uint8_t* value, size_t valuelen, uint8_t,
                     void* user_data) {
  Conn* c = (Conn*)user_data;
  const ng::nghttp2_frame_hd* hd = (const ng::nghttp2_frame_hd*)frame;
  if (hd->type != ng::NGHTTP2_HEADERS) return 0;
  StreamSt& st = c->streams[hd->stream_id];
  if (namelen == 5 && memcmp(name, ":path", 5) == 0) {
    static const char kCheck[] = "/envoy.service.auth.v3.Authorization/Check";
    static const char kHealth[] = "/grpc.health.v1.Health/Check";
    if (valuelen == sizeof(kCheck) - 1 && memcmp(value, kCheck, valuelen) == 0)
      st.kind = SK_CHECK;
    else if (valuelen == sizeof(kHealth) - 1 && memcmp(value, kHealth, valuelen) == 0)
      st.kind = SK_HEALTH;
    else
      st.kind = SK_OTHER;
  } else if (namelen == 13 && memcmp(name, "grpc-encoding", 13) == 0) {
    if (!(valuelen == 8 && memcmp(value, "identity", 8) == 0)) st.compressed = true;
  }
  return 0;
}

static int on_data_chunk(ng::nghttp2_session*, uint8_t, int32_t stream_id,
                         const uint8_t* data, size_t len, void* user_data) {
  Conn* c = (Conn*)user_data;
  auto it = c->streams.find(stream_id);
  if (it != c->streams.end()) {
    if (it->second.body.size() + len > (size_t)16 << 20) return 0;  // cap 16MB
    it->second.body.append((const char*)data, len);
  }
  return 0;
}

static int on_frame_recv(ng::nghttp2_session*, const void* frame, void* user_data) {
  Conn* c = (Conn*)user_data;
  const ng::nghttp2_frame_hd* hd = (const ng::nghttp2_frame_hd*)frame;
  if ((hd->type == ng::NGHTTP2_DATA || hd->type == ng::NGHTTP2_HEADERS) &&
      (hd->flags & ng::NGHTTP2_FLAG_END_STREAM)) {
    process_request(g_srv, c, hd->stream_id);
  }
  return 0;
}

static int on_stream_close(ng::nghttp2_session*, int32_t stream_id, uint32_t,
                           void* user_data) {
  Conn* c = (Conn*)user_data;
  c->streams.erase(stream_id);
  return 0;
}

// ---- epoll loop -----------------------------------------------------------

static void conn_close(Server* S, Conn* c) {
  {
    std::lock_guard<std::mutex> lk(S->mu);
    S->conns.erase(c->id);
  }
  epoll_ctl(S->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  if (c->sess) ng::api.session_del(c->sess);
  delete c;
}

// drain nghttp2's send queue into conn.outbuf, write once
static bool conn_pump(Server* S, Conn* c) {
  for (;;) {
    if (c->outbuf.size() < (size_t)256 << 10) {
      const uint8_t* data = nullptr;
      ssize_t n = ng::api.mem_send(c->sess, &data);
      if (n < 0) return false;
      if (n > 0) {
        c->outbuf.append((const char*)data, (size_t)n);
        continue;
      }
    }
    if (c->outbuf.empty()) break;
    ssize_t w = send(c->fd, c->outbuf.data(), c->outbuf.size(), MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (!c->want_eout) {
          struct epoll_event ev;
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.u32 = c->id;
          epoll_ctl(S->epfd, EPOLL_CTL_MOD, c->fd, &ev);
          c->want_eout = true;
        }
        return true;
      }
      return false;
    }
    c->outbuf.erase(0, (size_t)w);
    if (c->outbuf.empty() && !ng::api.want_write(c->sess)) break;
  }
  if (c->want_eout && c->outbuf.empty()) {
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.u32 = c->id;
    epoll_ctl(S->epfd, EPOLL_CTL_MOD, c->fd, &ev);
    c->want_eout = false;
  }
  return true;
}

static void accept_conns(Server* S) {
  for (;;) {
    int fd = accept4(S->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    Conn* c = new Conn();
    c->fd = fd;
    ng::nghttp2_session_callbacks* cbs = nullptr;
    ng::api.callbacks_new(&cbs);
    ng::api.set_on_header(cbs, on_header);
    ng::api.set_on_data_chunk(cbs, on_data_chunk);
    ng::api.set_on_frame_recv(cbs, on_frame_recv);
    ng::api.set_on_stream_close(cbs, on_stream_close);
    ng::api.session_server_new(&c->sess, cbs, c);
    ng::api.callbacks_del(cbs);
    ng::nghttp2_settings_entry iv[2] = {
        {ng::NGHTTP2_SETTINGS_MAX_CONCURRENT_STREAMS, 10000},  // ref main.go:68-69
        {ng::NGHTTP2_SETTINGS_INITIAL_WINDOW_SIZE, 1 << 20},
    };
    ng::api.submit_settings(c->sess, 0, iv, 2);
    // widen the connection receive window (auto-replenished by nghttp2)
    ng::api.submit_window_update(c->sess, 0, 0, (1 << 30) - 65535);
    {
      std::lock_guard<std::mutex> lk(S->mu);
      c->id = S->next_conn_id++;
      S->conns[c->id] = c;
    }
    S->n_conns.fetch_add(1, std::memory_order_relaxed);
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.u32 = c->id;
    epoll_ctl(S->epfd, EPOLL_CTL_ADD, fd, &ev);
    conn_pump(S, c);
  }
}

static void drain_done(Server* S) {
  std::deque<Done> q;
  {
    std::lock_guard<std::mutex> lk(S->done_mu);
    q.swap(S->done_q);
  }
  std::vector<Conn*> touched;
  for (Done& d : q) {
    Conn* c;
    {
      std::lock_guard<std::mutex> lk(S->mu);
      auto it = S->conns.find(d.conn_id);
      c = it == S->conns.end() ? nullptr : it->second;
    }
    if (!c) continue;
    if (d.grpc_status) submit_grpc_error(c, d.stream_id, d.grpc_status);
    else submit_grpc_response(c, d.stream_id, d.msg);
    if (d.t_done)
      S->stage_respond[stage_bucket(now_mono_ns() - d.t_done)].fetch_add(
          1, std::memory_order_relaxed);
    if (std::find(touched.begin(), touched.end(), c) == touched.end())
      touched.push_back(c);
  }
  for (Conn* c : touched)
    if (!conn_pump(S, c)) conn_close(S, c);
}

static void epoll_loop(Server* S) {
  struct epoll_event evs[64];
  while (S->running.load(std::memory_order_relaxed)) {
    int n = epoll_wait(S->epfd, evs, 64, 100);
    for (int i = 0; i < n; ++i) {
      uint32_t id = evs[i].data.u32;
      if (id == 0xFFFFFFFFu) {  // listen fd
        accept_conns(S);
        continue;
      }
      if (id == 0xFFFFFFFEu) {  // eventfd: completions pending
        uint64_t v;
        while (read(S->evfd, &v, 8) == 8) {}
        drain_done(S);
        continue;
      }
      if (id == 0xFFFFFFFDu) {  // timerfd: micro-batch window expired
        uint64_t v;
        while (read(S->tfd, &v, 8) == 8) {}
        flush_batch(S, /*from_timer=*/true);
        continue;
      }
      Conn* c;
      {
        std::lock_guard<std::mutex> lk(S->mu);
        auto it = S->conns.find(id);
        c = it == S->conns.end() ? nullptr : it->second;
      }
      if (!c) continue;
      bool dead = false;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
      if (!dead && (evs[i].events & EPOLLIN)) {
        char buf[65536];
        for (;;) {
          ssize_t r = recv(c->fd, buf, sizeof buf, 0);
          if (r > 0) {
            ssize_t rc = ng::api.mem_recv(c->sess, (const uint8_t*)buf, (size_t)r);
            if (rc < 0) { dead = true; break; }
            if (r < (ssize_t)sizeof buf) break;
          } else if (r == 0) { dead = true; break; }
          else {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            dead = true; break;
          }
        }
      }
      if (!dead) dead = !conn_pump(S, c);
      if (dead) conn_close(S, c);
    }
  }
  // shutdown: close all conns, notify waiters
  std::vector<Conn*> all;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    for (auto& kv : S->conns) all.push_back(kv.second);
    S->conns.clear();
  }
  for (Conn* c : all) {
    epoll_ctl(S->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
    if (c->sess) ng::api.session_del(c->sess);
    delete c;
  }
  {
    std::lock_guard<std::mutex> lk(S->batch_mu);
    S->batch_events.push_back({EV_STOPPED, 0, 0, 0});
  }
  S->batch_cv.notify_all();
  S->slow_cv.notify_all();
}

// ---- control-plane entry points (called from Python with GIL held, except
// the waits which release it in pymod) ---------------------------------------

static int server_start(Server* S) {
  if (!ng::load()) return -1;
  S->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (S->listen_fd < 0) return -2;
  int one = 1;
  setsockopt(S->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(S->any_addr ? INADDR_ANY : INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)S->port);
  if (bind(S->listen_fd, (struct sockaddr*)&addr, sizeof addr) < 0 ||
      listen(S->listen_fd, 1024) < 0) {
    close(S->listen_fd);  // error paths must not leak the socket
    S->listen_fd = -1;
    return -3;
  }
  socklen_t alen = sizeof addr;
  getsockname(S->listen_fd, (struct sockaddr*)&addr, &alen);
  S->bound_port = ntohs(addr.sin_port);
  S->epfd = epoll_create1(0);
  S->evfd = eventfd(0, EFD_NONBLOCK);
  S->tfd = timerfd_create(CLOCK_MONOTONIC, TFD_NONBLOCK);
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u32 = 0xFFFFFFFFu;
  epoll_ctl(S->epfd, EPOLL_CTL_ADD, S->listen_fd, &ev);
  ev.data.u32 = 0xFFFFFFFEu;
  epoll_ctl(S->epfd, EPOLL_CTL_ADD, S->evfd, &ev);
  ev.data.u32 = 0xFFFFFFFDu;
  epoll_ctl(S->epfd, EPOLL_CTL_ADD, S->tfd, &ev);
  S->running.store(true);
  S->thr = std::thread(epoll_loop, S);
  return 0;
}

static void server_stop(Server* S) {
  if (!S->running.exchange(false)) return;
  if (S->thr.joinable()) S->thr.join();
  if (S->listen_fd >= 0) close(S->listen_fd);
  if (S->epfd >= 0) close(S->epfd);
  if (S->evfd >= 0) close(S->evfd);
  if (S->tfd >= 0) close(S->tfd);
}

static void wake_epoll(Server* S) {
  uint64_t one = 1;
  ssize_t r = write(S->evfd, &one, 8);
  (void)r;
}

// retire check: emit SNAP_RETIRED for non-current snapshots with no pending
// batches, and ERASE them from the registry — retired snapshots hold
// dangling pointers (numpy slots, interner) once Python frees its side, and
// an append-only map would leak a full corpus copy per reconcile.
// Call under S->mu.
static void maybe_retire_locked(Server* S, std::vector<int64_t>& retired) {
  for (auto it = S->snaps.begin(); it != S->snaps.end();) {
    Snapshot* sn = it->second.get();
    if (it->second != S->cur && sn->pending_batches == 0 && sn != S->epoll_pin &&
        (S->fill_snap == nullptr || S->fill_snap.get() != sn)) {
      // undrained direct-decision counters survive retirement in the
      // leftover map so no metric increment is lost
      for (size_t f = 0; sn->fc_counts && f < sn->fcs.size(); ++f) {
        uint64_t ok = sn->fc_counts[3 * f].exchange(0);
        uint64_t mi = sn->fc_counts[3 * f + 1].exchange(0);
        uint64_t inv = sn->fc_counts[3 * f + 2].exchange(0);
        if (ok | mi | inv) {
          auto& agg = S->fc_leftover[sn->fcs[f].ns + '\x1f' + sn->fcs[f].name];
          agg[0] += ok;
          agg[1] += mi;
          agg[2] += inv;
        }
      }
      // same for undrained duration-histogram buckets
      for (size_t f = 0; sn->fc_durs && f < sn->fcs.size(); ++f) {
        uint64_t any = 0;
        uint64_t vals[DUR_STRIDE];
        for (int k = 0; k < DUR_STRIDE; ++k)
          any |= (vals[k] = sn->fc_durs[f * DUR_STRIDE + k].exchange(0));
        if (any) {
          auto& agg = S->dur_leftover[sn->fcs[f].ns + '\x1f' + sn->fcs[f].name];
          for (int k = 0; k < DUR_STRIDE; ++k) agg[k] += vals[k];
        }
      }
      retired.push_back(sn->id);
      it = S->snaps.erase(it);
    } else {
      ++it;
    }
  }
}

// drain per-authconfig direct-decision counters (all live snapshots + the
// leftovers of retired ones) into `out`, keyed ns+'\x1f'+name
static void drain_fc_counts(
    Server* S, std::unordered_map<std::string, std::array<uint64_t, 3>>& out) {
  std::lock_guard<std::mutex> lk(S->mu);
  for (auto& kv : S->snaps) {
    Snapshot* sn = kv.second.get();
    for (size_t f = 0; sn->fc_counts && f < sn->fcs.size(); ++f) {
      uint64_t ok = sn->fc_counts[3 * f].exchange(0);
      uint64_t mi = sn->fc_counts[3 * f + 1].exchange(0);
      uint64_t inv = sn->fc_counts[3 * f + 2].exchange(0);
      if (ok | mi | inv) {
        auto& agg = out[sn->fcs[f].ns + '\x1f' + sn->fcs[f].name];
        agg[0] += ok;
        agg[1] += mi;
        agg[2] += inv;
      }
    }
  }
  for (auto& kv : S->fc_leftover) {
    auto& agg = out[kv.first];
    agg[0] += kv.second[0];
    agg[1] += kv.second[1];
    agg[2] += kv.second[2];
  }
  S->fc_leftover.clear();
}

// drain per-authconfig duration histograms (live snapshots + retired
// leftovers) into `out`, keyed ns+'\x1f'+name → [15 buckets, sum_ns]
static void drain_durations(
    Server* S, std::unordered_map<std::string, std::array<uint64_t, DUR_STRIDE>>& out) {
  std::lock_guard<std::mutex> lk(S->mu);
  for (auto& kv : S->snaps) {
    Snapshot* sn = kv.second.get();
    for (size_t f = 0; sn->fc_durs && f < sn->fcs.size(); ++f) {
      uint64_t any = 0;
      uint64_t vals[DUR_STRIDE];
      for (int k = 0; k < DUR_STRIDE; ++k)
        any |= (vals[k] = sn->fc_durs[f * DUR_STRIDE + k].exchange(0));
      if (any) {
        auto& agg = out[sn->fcs[f].ns + '\x1f' + sn->fcs[f].name];
        for (int k = 0; k < DUR_STRIDE; ++k) agg[k] += vals[k];
      }
    }
  }
  for (auto& kv : S->dur_leftover) {
    auto& agg = out[kv.first];
    for (int k = 0; k < DUR_STRIDE; ++k) agg[k] += kv.second[k];
  }
  S->dur_leftover.clear();
}

static void emit_retired(Server* S, const std::vector<int64_t>& retired) {
  if (retired.empty()) return;
  {
    std::lock_guard<std::mutex> lk(S->batch_mu);
    for (int64_t id : retired) S->batch_events.push_back({EV_SNAP_RETIRED, id, 0, 0});
  }
  S->batch_cv.notify_all();
}

static void complete_batch(Server* S, int64_t snap_id, int slot, const uint8_t* verdict) {
  std::shared_ptr<Snapshot> snap;
  std::vector<Entry> entries;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    auto it = S->snaps.find(snap_id);
    if (it == S->snaps.end()) return;
    snap = it->second;
    entries.swap(snap->slot_entries[slot]);
  }
  uint64_t allowed = 0, handed_off = 0;
  const int64_t t_now = now_mono_ns();
  const int64_t t_flush = snap->slot_flush_ns[slot];
  const int exec_b = stage_bucket(t_now - t_flush);
  // hybrid kernel-PASS entries: collected under mu, enqueued to the slow
  // lane after (push ordering mirrors push_slow: mu for slow_pending,
  // then slow_mu — never nested)
  struct Handoff { uint32_t conn_id; int32_t stream_id; std::string raw; };
  std::vector<Handoff> handoffs;
  std::deque<Done> dones;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    for (size_t i = 0; i < entries.size(); ++i) {
      Entry& e = entries[i];
      const FastConfig& fc = snap->fcs[e.fc];
      bool ok = verdict[i] != 0;
      if (ok && fc.hybrid) {
        handed_off++;
        handoffs.push_back({e.conn_id, e.stream_id, std::move(e.raw)});
        continue;
      }
      allowed += ok;
      dones.push_back(
          {e.conn_id, e.stream_id,
           ok ? (e.ok_msg ? *e.ok_msg : fc.ok_msg)
              : (e.deny_msg ? *e.deny_msg : fc.deny_msg),
           0, t_now});
    }
    snap->free_slots.push_back(slot);
    snap->pending_batches--;
  }
  for (Handoff& h : handoffs) {
    uint64_t id = 0;
    bool shed = false;
    {
      std::lock_guard<std::mutex> lk(S->mu);
      if (S->slow_pending.size() >= S->slow_cap) {
        shed = true;
      } else {
        id = S->next_slow_id++;
        S->slow_pending[id] = {h.conn_id, h.stream_id};
      }
    }
    if (shed) {
      dones.push_back({h.conn_id, h.stream_id, std::string(), 8, 0});
      S->n_slow_shed.fetch_add(1, std::memory_order_relaxed);
      continue;
    }
    {
      std::lock_guard<std::mutex> lk(S->slow_mu);
      S->slow_q.push_back({id, std::move(h.raw)});
    }
    S->n_slow.fetch_add(1, std::memory_order_relaxed);
  }
  if (!handoffs.empty()) S->slow_cv.notify_all();
  if (!dones.empty()) {
    std::lock_guard<std::mutex> lk(S->done_mu);
    for (Done& d : dones) S->done_q.push_back(std::move(d));
  }
  // per-request on-box stages + the duration series the pipeline observes
  // (ref pkg/service/auth_pipeline.go:26-36): all clocked here, no tunnel.
  // Hybrid handoffs skip the duration series — the Python pipeline they
  // continue into observes them itself (no double counting)
  for (size_t i = 0; i < entries.size(); ++i) {
    const Entry& e = entries[i];
    S->stage_wait[stage_bucket(t_flush - e.t_enq)].fetch_add(
        1, std::memory_order_relaxed);
    S->stage_exec[exec_b].fetch_add(1, std::memory_order_relaxed);
    if (verdict[i] != 0 && snap->fcs[e.fc].hybrid) continue;
    if (snap->fc_durs) {
      int64_t dur = t_now - e.t_enq;
      auto* d = &snap->fc_durs[(size_t)e.fc * DUR_STRIDE];
      d[dur_bucket(dur)].fetch_add(1, std::memory_order_relaxed);
      d[N_DUR_BUCKETS].fetch_add((uint64_t)dur, std::memory_order_relaxed);
    }
  }
  S->n_hybrid.fetch_add(handed_off, std::memory_order_relaxed);
  S->n_allowed.fetch_add(allowed, std::memory_order_relaxed);
  S->n_denied.fetch_add(entries.size() - handed_off - allowed,
                        std::memory_order_relaxed);
  std::vector<int64_t> retired;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    maybe_retire_locked(S, retired);
  }
  emit_retired(S, retired);
  wake_epoll(S);
}

// register (or refresh) a runtime plan variant for one credential — the
// slow lane calls this after a successful token verification.  Overwrites
// swap the shared_ptr (a mid-request reader holds its own reference), so
// stale plan vectors free as soon as the last reader drops.  Returns false
// when the snapshot is gone (stale registration: harmless no-op) or the
// cap is hit.
static bool add_variant(Server* S, int64_t snap_id, int32_t fc_idx,
                        int32_t src_idx, std::string cred,
                        std::vector<FastPlan> plans, std::string ok_bytes,
                        std::string deny_bytes, int64_t exp_ns) {
  std::shared_ptr<Snapshot> snap;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    auto it = S->snaps.find(snap_id);
    if (it == S->snaps.end()) return false;
    snap = it->second;
  }
  if (fc_idx < 0 || (size_t)fc_idx >= snap->fcs.size()) return false;
  FastConfig& fc = snap->fcs[fc_idx];
  if (src_idx < 0 || (size_t)src_idx >= fc.sources.size()) return false;
  CredSource& src = fc.sources[src_idx];
  if (!src.dyn) return false;
  auto sp = std::make_shared<const std::vector<FastPlan>>(std::move(plans));
  std::shared_ptr<const std::string> ok;
  if (!ok_bytes.empty())
    ok = std::make_shared<const std::string>(std::move(ok_bytes));
  std::shared_ptr<const std::string> deny;
  if (!deny_bytes.empty())
    deny = std::make_shared<const std::string>(std::move(deny_bytes));
  {
    std::lock_guard<std::mutex> vlk(snap->var_mu);
    auto it = src.dyn_variants.find(cred);
    if (it == src.dyn_variants.end() &&
        src.dyn_variants.size() >= DYN_VARIANT_CAP) {
      // sweep expired entries once; if still full, the slow lane keeps
      // serving this token (correct, just not fast)
      int64_t now = now_realtime_ns();
      for (auto sit = src.dyn_variants.begin(); sit != src.dyn_variants.end();)
        sit = sit->second.exp_ns <= now ? src.dyn_variants.erase(sit)
                                        : std::next(sit);
      if (src.dyn_variants.size() >= DYN_VARIANT_CAP) return false;
      it = src.dyn_variants.end();
    }
    if (it != src.dyn_variants.end())
      it->second = {std::move(sp), exp_ns, std::move(ok), std::move(deny)};
    else
      src.dyn_variants.emplace(
          std::move(cred),
          CredSource::DynVar{std::move(sp), exp_ns, std::move(ok),
                             std::move(deny)});
  }
  S->n_dyn_add.fetch_add(1, std::memory_order_relaxed);
  return true;
}

static void complete_slow(Server* S, uint64_t req_id, const char* msg, size_t n,
                          int grpc_status) {
  SlowPending sp;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    auto it = S->slow_pending.find(req_id);
    if (it == S->slow_pending.end()) return;
    sp = it->second;
    S->slow_pending.erase(it);
  }
  bool was_empty;
  {
    std::lock_guard<std::mutex> lk(S->done_mu);
    was_empty = S->done_q.empty();
    S->done_q.push_back({sp.conn_id, sp.stream_id, std::string(msg, n),
                         grpc_status, now_mono_ns()});
  }
  // coalesce wakes: drain_done swaps the WHOLE queue under done_mu, so a
  // non-empty observation means a wake is already owed — the eventfd
  // write per completion was a measurable share of the slow lane's budget
  if (was_empty) wake_epoll(S);
}

// batch form: the Python slow lane buffers finished responses and a
// dedicated completer thread lands N of them in two lock rounds + at most
// one wake — per-response mutex/wake traffic was ~35µs of contended wall
// on the asyncio thread
struct SlowDone { uint64_t req_id; std::string msg; int grpc_status; };

static void complete_slow_many(Server* S, std::vector<SlowDone>& items) {
  std::deque<Done> dones;
  const int64_t t_now = now_mono_ns();
  {
    std::lock_guard<std::mutex> lk(S->mu);
    for (SlowDone& sd : items) {
      auto it = S->slow_pending.find(sd.req_id);
      if (it == S->slow_pending.end()) continue;
      dones.push_back({it->second.conn_id, it->second.stream_id,
                       std::move(sd.msg), sd.grpc_status, t_now});
      S->slow_pending.erase(it);
    }
  }
  if (dones.empty()) return;
  bool was_empty;
  {
    std::lock_guard<std::mutex> lk(S->done_mu);
    was_empty = S->done_q.empty();
    for (Done& d : dones) S->done_q.push_back(std::move(d));
  }
  if (was_empty) wake_epoll(S);
}

}  // namespace fe
