// CPython extension front-end for the native encoder.
//
// Adds a direct PyObject-walk encode path: resolves selectors over the
// Authorization-JSON dicts in place (no json.dumps → parse round-trip),
// renders with the same gjson-String semantics, and scatters into the numpy
// buffers.  Holds the GIL (it touches Python objects); the JSON-blob path in
// encoder.cpp stays available for GIL-free multithreaded encoding on
// many-core hosts.  Both share Policy/Interner/render/leaf-pass code — this
// file #includes encoder.cpp as a single translation unit.
//
// Build (one shared object, importable AND ctypes-loadable):
//   g++ -O2 -std=c++17 -shared -fPIC -pthread -I$(python-include) \
//       pymod.cpp -o _atpuenc.so

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "encoder.cpp"
#include "frontend.cpp"

namespace {

PyObject* g_json_dumps = nullptr;   // json.dumps
PyObject* g_dumps_kwargs = nullptr; // {"separators": (",", ":"), "ensure_ascii": False}

void policy_capsule_free(PyObject* cap) {
  Policy* p = (Policy*)PyCapsule_GetPointer(cap, "atpu.Policy");
  delete p;
}

// render a Python value with compiler/encode.py::_render semantics.
// returns false if a Python error occurred (non-serializable nested value).
bool render_py(PyObject* v, std::string& out) {
  if (v == nullptr || v == Py_None) return true;  // ""
  if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(v, &n);
    if (s == nullptr) return false;
    out.append(s, (size_t)n);
    return true;
  }
  if (PyBool_Check(v)) {  // before PyLong: bool subclasses int
    out += (v == Py_True) ? "true" : "false";
    return true;
  }
  if (PyLong_Check(v)) {
    int overflow_flag = 0;
    long long ll = PyLong_AsLongLongAndOverflow(v, &overflow_flag);
    if (!overflow_flag && !(ll == -1 && PyErr_Occurred())) {
      char buf[32];
      auto res = std::to_chars(buf, buf + sizeof buf, ll);
      out.append(buf, res.ptr - buf);
      return true;
    }
    PyErr_Clear();
    PyObject* s = PyObject_Str(v);  // big ints
    if (s == nullptr) return false;
    Py_ssize_t n;
    const char* cs = PyUnicode_AsUTF8AndSize(s, &n);
    if (cs == nullptr) { Py_DECREF(s); return false; }
    out.append(cs, (size_t)n);
    Py_DECREF(s);
    return true;
  }
  if (PyFloat_Check(v)) {
    num_str(PyFloat_AS_DOUBLE(v), out);
    return true;
  }
  // dict/list/other → compact raw JSON via the real json.dumps (exact parity
  // with authjson.selector.to_raw_json by construction)
  PyObject* args = PyTuple_Pack(1, v);
  if (args == nullptr) return false;
  PyObject* s = PyObject_Call(g_json_dumps, args, g_dumps_kwargs);
  Py_DECREF(args);
  if (s == nullptr) return false;
  Py_ssize_t n;
  const char* cs = PyUnicode_AsUTF8AndSize(s, &n);
  if (cs == nullptr) { Py_DECREF(s); return false; }
  out.append(cs, (size_t)n);
  Py_DECREF(s);
  return true;
}

// walk a plain dot-path over Python dicts/lists; returns borrowed ref or
// nullptr for missing.  seg_objs are pre-built PyUnicode keys (hash cached).
PyObject* walk_py(PyObject* doc, const Policy* p, PyObject* seg_objs, int32_t attr) {
  PyObject* cur = doc;
  for (int32_t s = p->attr_seg_offs[attr]; s < p->attr_seg_offs[attr + 1]; ++s) {
    if (cur == nullptr) return nullptr;
    if (PyDict_Check(cur)) {
      cur = PyDict_GetItem(cur, PyTuple_GET_ITEM(seg_objs, s));  // borrowed
    } else if (PyList_Check(cur)) {
      const char* kp = p->strings.data() + p->seg_views[s].first;
      int32_t klen = p->seg_views[s].second;
      const char* q = kp; const char* qe = kp + klen;
      while (q < qe && (*q == ' ' || *q == '\t')) ++q;
      while (qe > q && (qe[-1] == ' ' || qe[-1] == '\t')) --qe;
      bool neg = false;
      if (q < qe && (*q == '+' || *q == '-')) { neg = (*q == '-'); ++q; }
      if (q == qe) return nullptr;
      Py_ssize_t len = PyList_GET_SIZE(cur);
      int64_t idx = 0;
      for (; q < qe; ++q) {
        if (*q < '0' || *q > '9') return nullptr;
        idx = idx * 10 + (*q - '0');
        if (idx > len) break;
      }
      if (neg || idx >= len || q != qe) {
        // re-check: digits ran clean only if q reached qe
        if (q != qe) return nullptr;
        return nullptr;
      }
      cur = PyList_GET_ITEM(cur, (Py_ssize_t)idx);
    } else {
      return nullptr;
    }
  }
  return cur;
}

// encode_docs(policy_capsule, seg_objs, docs, rows_addr, n_docs,
//             A, K, L, NB, DVB,
//             attrs_val, attrs_members, overflow, cpu_lane, attr_bytes, byte_ovf,
//             task_r, task_leaf, task_val_off, task_val_len, max_tasks,
//             arena_addr, arena_cap, elem16)
//             (all *_addr are numpy .ctypes.data ints; elem16: id buffers
//              are int16 when the interner fits — see pack.wire_dtype)
PyObject* encode_docs(PyObject*, PyObject* args) {
  PyObject* cap; PyObject* seg_objs; PyObject* docs;
  unsigned long long rows_a, av_a, am_a, ov_a, cl_a, ab_a, bo_a;
  unsigned long long tr_a, tl_a, to_a, tv_a, arena_a;
  int n_docs, A, K, L, NB, DVB, max_tasks, elem16;
  long long arena_cap;
  if (!PyArg_ParseTuple(
          args, "OOOKiiiiiiKKKKKKKKKKiKLi",
          &cap, &seg_objs, &docs, &rows_a, &n_docs, &A, &K, &L, &NB, &DVB,
          &av_a, &am_a, &ov_a, &cl_a, &ab_a, &bo_a,
          &tr_a, &tl_a, &to_a, &tv_a, &max_tasks, &arena_a, &arena_cap,
          &elem16))
    return nullptr;
  Policy* p = (Policy*)PyCapsule_GetPointer(cap, "atpu.Policy");
  if (p == nullptr) return nullptr;
  const int32_t* rows = (const int32_t*)rows_a;
  void* attrs_val = (void*)av_a;
  void* attrs_members = (void*)am_a;
  uint8_t* overflow = (uint8_t*)ov_a;
  uint8_t* cpu_lane = (uint8_t*)cl_a;
  uint8_t* attr_bytes = (uint8_t*)ab_a;
  uint8_t* byte_ovf = (uint8_t*)bo_a;

  std::vector<int32_t> attr_epoch((size_t)A, -1);
  std::vector<std::string> attr_rendered((size_t)A);
  std::vector<std::vector<int32_t>> attr_elem_ids((size_t)A);
  std::vector<Task> tasks;
  std::string tmp;

  for (int32_t r = 0; r < n_docs; ++r) {
    PyObject* doc = PyList_GET_ITEM(docs, r);
    int32_t row = rows[r];
    for (int32_t ai = p->cfg_attr_offs[row]; ai < p->cfg_attr_offs[row + 1]; ++ai) {
      int32_t attr = p->cfg_attr_idx[ai];
      if (p->attr_complex[attr]) continue;
      PyObject* v = walk_py(doc, p, seg_objs, attr);
      attr_epoch[attr] = r;
      std::string& rendered = attr_rendered[attr];
      rendered.clear();
      if (!render_py(v, rendered)) return nullptr;
      int32_t vid = p->interner.lookup(rendered.data(), rendered.size());
      store_id(attrs_val, (int64_t)r * A + attr, vid, elem16);
      int32_t slot = p->attr_byte_slot[attr];
      if (slot >= 0) {
        if ((int64_t)rendered.size() > DVB ||
            memchr(rendered.data(), 0, rendered.size()) != nullptr) {
          byte_ovf[(int64_t)r * NB + slot] = 1;
        } else if (!rendered.empty()) {
          memcpy(attr_bytes + ((int64_t)r * NB + slot) * DVB, rendered.data(),
                 rendered.size());
        }
      }
      std::vector<int32_t>& elems = attr_elem_ids[attr];
      elems.clear();
      if (v != nullptr && PyList_Check(v)) {
        Py_ssize_t n = PyList_GET_SIZE(v);
        for (Py_ssize_t k = 0; k < n; ++k) {
          tmp.clear();
          if (!render_py(PyList_GET_ITEM(v, k), tmp)) return nullptr;
          int32_t eid = p->interner.lookup(tmp.data(), tmp.size());
          elems.push_back(eid);
          if (k < K) store_id(attrs_members, ((int64_t)r * A + attr) * K + k, eid, elem16);
        }
        if ((int64_t)n > K) overflow[(int64_t)r * A + attr] = 1;
      } else if (v != nullptr && v != Py_None) {
        store_id(attrs_members, ((int64_t)r * A + attr) * K, vid, elem16);
        elems.push_back(vid);
      }
    }
    process_cpu_leaves(p, r, row, attr_epoch, attr_rendered, attr_elem_ids,
                       A, L, NB, byte_ovf, overflow, cpu_lane, tasks);
  }

  int64_t n_tasks = merge_tasks(&tasks, 1, (int32_t*)tr_a, (int32_t*)tl_a,
                                (int64_t*)to_a, (int32_t*)tv_a, max_tasks,
                                (char*)arena_a, arena_cap);
  return PyLong_FromLongLong(n_tasks);
}

// policy_new_py(intern_blob, intern_offs_addr, intern_ids_addr, n_intern,
//               n_attrs, seg_blob, seg_offs_addr, n_segs, attr_seg_offs_addr,
//               attr_complex_addr, attr_byte_slot_addr,
//               n_leaves, leaf_op_addr, leaf_attr_addr, leaf_const_addr,
//               n_configs, cfg_attr_offs_addr, cfg_attr_idx_addr,
//               cfg_cpu_offs_addr, cfg_cpu_idx_addr, members_k, dvb, nb)
PyObject* policy_new_py(PyObject*, PyObject* args) {
  Py_buffer intern_blob, seg_blob;
  unsigned long long io_a, ii_a, so_a, aso_a, ac_a, abs_a;
  unsigned long long lo_a, la_a, lc_a, cao_a, cai_a, cco_a, cci_a;
  int n_intern, n_attrs, n_segs, n_leaves, n_configs, members_k, dvb, nb;
  if (!PyArg_ParseTuple(
          args, "y*KKiiy*KiKKKiKKKiKKKKiii",
          &intern_blob, &io_a, &ii_a, &n_intern,
          &n_attrs, &seg_blob, &so_a, &n_segs, &aso_a, &ac_a, &abs_a,
          &n_leaves, &lo_a, &la_a, &lc_a,
          &n_configs, &cao_a, &cai_a, &cco_a, &cci_a,
          &members_k, &dvb, &nb))
    return nullptr;
  Policy* p = atpu_policy_new(
      (const char*)intern_blob.buf, (const int64_t*)io_a, (const int32_t*)ii_a,
      n_intern, n_attrs, (const char*)seg_blob.buf, (const int64_t*)so_a,
      n_segs, (const int32_t*)aso_a, (const uint8_t*)ac_a, (const int32_t*)abs_a,
      n_leaves, (const int32_t*)lo_a, (const int32_t*)la_a, (const int32_t*)lc_a,
      n_configs, (const int32_t*)cao_a, (const int32_t*)cai_a,
      (const int32_t*)cco_a, (const int32_t*)cci_a, members_k, dvb, nb);
  PyBuffer_Release(&intern_blob);
  PyBuffer_Release(&seg_blob);
  return PyCapsule_New(p, "atpu.Policy", policy_capsule_free);
}

// encode_json_py(policy_capsule, blob, doc_offs_addr, n_docs, rows_addr,
//                A, K, L, NB, DVB, <6 out addrs>, <4 task addrs>, max_tasks,
//                arena_addr, arena_cap, n_threads, elem16)
// GIL released around the C encode (threaded path for many-core hosts).
PyObject* encode_json_py(PyObject*, PyObject* args) {
  PyObject* cap; Py_buffer blob;
  unsigned long long do_a, rows_a, av_a, am_a, ov_a, cl_a, ab_a, bo_a;
  unsigned long long tr_a, tl_a, to_a, tv_a, arena_a;
  int n_docs, A, K, L, NB, DVB, max_tasks, n_threads, elem16;
  long long arena_cap;
  if (!PyArg_ParseTuple(
          args, "Oy*KiKiiiiiKKKKKKKKKKiKLii",
          &cap, &blob, &do_a, &n_docs, &rows_a, &A, &K, &L, &NB, &DVB,
          &av_a, &am_a, &ov_a, &cl_a, &ab_a, &bo_a,
          &tr_a, &tl_a, &to_a, &tv_a, &max_tasks, &arena_a, &arena_cap,
          &n_threads, &elem16))
    return nullptr;
  Policy* p = (Policy*)PyCapsule_GetPointer(cap, "atpu.Policy");
  if (p == nullptr) { PyBuffer_Release(&blob); return nullptr; }
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = atpu_encode(p, (const char*)blob.buf, (const int64_t*)do_a, n_docs,
                   (const int32_t*)rows_a, A, K, L, NB, DVB,
                   (void*)av_a, (void*)am_a, (uint8_t*)ov_a,
                   (uint8_t*)cl_a, (uint8_t*)ab_a, (uint8_t*)bo_a,
                   (int32_t*)tr_a, (int32_t*)tl_a, (int64_t*)to_a,
                   (int32_t*)tv_a, max_tasks, (char*)arena_a, arena_cap,
                   n_threads, elem16);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&blob);
  return PyLong_FromLongLong(rc);
}

// ---------------------------------------------------------------------------
// native gRPC frontend (native/frontend.cpp)
// ---------------------------------------------------------------------------

static long dict_int(PyObject* d, const char* k, long dflt = 0) {
  PyObject* v = PyDict_GetItemString(d, k);
  return v ? PyLong_AsLong(v) : dflt;
}

static unsigned long long dict_addr(PyObject* d, const char* k) {
  PyObject* v = PyDict_GetItemString(d, k);
  return v ? PyLong_AsUnsignedLongLong(v) : 0;
}

static bool dict_bytes(PyObject* d, const char* k, std::string& out) {
  PyObject* v = PyDict_GetItemString(d, k);
  if (v == nullptr || !PyBytes_Check(v)) return false;
  out.assign(PyBytes_AS_STRING(v), (size_t)PyBytes_GET_SIZE(v));
  return true;
}

static bool dict_str(PyObject* d, const char* k, std::string& out) {
  PyObject* v = PyDict_GetItemString(d, k);
  if (v == nullptr || !PyUnicode_Check(v)) return false;
  Py_ssize_t n;
  const char* s = PyUnicode_AsUTF8AndSize(v, &n);
  if (s == nullptr) return false;
  out.assign(s, (size_t)n);
  return true;
}

// plan tuple list (runtime/native_frontend.py plan format) → FastPlan vector
static bool parse_plans(PyObject* plans, std::vector<fe::FastPlan>& out,
                        bool* needs_split) {
  for (Py_ssize_t j = 0; plans != nullptr && j < PyList_GET_SIZE(plans); ++j) {
    PyObject* t = PyList_GET_ITEM(plans, j);
    fe::FastPlan pl;
    pl.attr = (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(t, 0));
    pl.kind = (int)PyLong_AsLong(PyTuple_GET_ITEM(t, 1));
    Py_ssize_t kn;
    const char* ks = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(t, 2), &kn);
    if (ks == nullptr) return false;
    pl.key.assign(ks, (size_t)kn);
    pl.const_vid = (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(t, 3));
    pl.const_missing = PyObject_IsTrue(PyTuple_GET_ITEM(t, 4)) == 1;
    PyObject* mems = PyTuple_GET_ITEM(t, 5);
    for (Py_ssize_t m = 0; m < PyList_GET_SIZE(mems); ++m)
      pl.const_members.push_back((int32_t)PyLong_AsLong(PyList_GET_ITEM(mems, m)));
    PyObject* cb = PyTuple_GET_ITEM(t, 6);
    pl.const_bytes.assign(PyBytes_AS_STRING(cb), (size_t)PyBytes_GET_SIZE(cb));
    pl.const_byte_ovf = PyObject_IsTrue(PyTuple_GET_ITEM(t, 7)) == 1;
    if (needs_split && (pl.kind == fe::K_URL_PATH || pl.kind == fe::K_QUERY))
      *needs_split = true;
    out.push_back(std::move(pl));
  }
  return true;
}

// fe_start(port, bmax, nslots, window_us, slow_cap, health_bytes, any_addr) -> 0
PyObject* fe_start_py(PyObject*, PyObject* args) {
  int port, bmax, nslots, any_addr = 0;
  long window_us, slow_cap;
  Py_buffer health;
  if (!PyArg_ParseTuple(args, "iiilly*|i", &port, &bmax, &nslots, &window_us,
                        &slow_cap, &health, &any_addr))
    return nullptr;
  if (fe::g_srv != nullptr) {
    PyBuffer_Release(&health);
    PyErr_SetString(PyExc_RuntimeError, "frontend already started");
    return nullptr;
  }
  fe::Server* S = new fe::Server();
  S->port = port;
  S->any_addr = any_addr != 0;
  S->bmax = bmax;
  S->nslots = nslots;
  S->window_us = window_us;
  S->slow_cap = (size_t)slow_cap;
  S->health_msg.assign((const char*)health.buf, (size_t)health.len);
  PyBuffer_Release(&health);
  int rc = fe::server_start(S);
  if (rc != 0) {
    delete S;
    return PyLong_FromLong(rc);
  }
  fe::g_srv = S;
  return PyLong_FromLong(0);
}

PyObject* fe_port_py(PyObject*, PyObject*) {
  return PyLong_FromLong(fe::g_srv ? fe::g_srv->bound_port : -1);
}

PyObject* fe_stop_py(PyObject*, PyObject*) {
  fe::Server* S = fe::g_srv;
  if (S != nullptr) {
    Py_BEGIN_ALLOW_THREADS
    fe::server_stop(S);
    Py_END_ALLOW_THREADS
    fe::g_srv = nullptr;
    // leak the Server struct intentionally: Python threads may still be
    // inside fe_wait_* draining the final STOPPED event
  }
  Py_RETURN_NONE;
}

// fe_swap(spec_dict) -> 0; spec described in runtime/native_frontend.py
PyObject* fe_swap_py(PyObject*, PyObject* args) {
  PyObject* d;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &d)) return nullptr;
  fe::Server* S = fe::g_srv;
  if (S == nullptr) {
    PyErr_SetString(PyExc_RuntimeError, "frontend not started");
    return nullptr;
  }
  auto snap = std::make_shared<fe::Snapshot>();
  snap->id = dict_int(d, "snap_id");
  PyObject* cap = PyDict_GetItemString(d, "policy");
  if (cap != nullptr && cap != Py_None) {
    Policy* p = (Policy*)PyCapsule_GetPointer(cap, "atpu.Policy");
    if (p == nullptr) return nullptr;
    snap->interner = &p->interner;
  }
  snap->A = (int)dict_int(d, "A");
  snap->M = (int)dict_int(d, "M");
  snap->K = (int)dict_int(d, "K");
  snap->C = (int)dict_int(d, "C");
  snap->NB = (int)dict_int(d, "NB");
  snap->DVB = (int)dict_int(d, "DVB");
  snap->elem16 = dict_int(d, "elem16") != 0;
  snap->trace_every = dict_int(d, "trace_every", 0);
  snap->S = (int)dict_int(d, "S", 1);
  if (snap->S < 1) snap->S = 1;
  const long SA = (long)snap->S * snap->A;
  const int32_t* ams = (const int32_t*)dict_addr(d, "attr_member_slot_addr");
  const int32_t* abs_v = (const int32_t*)dict_addr(d, "attr_byte_slot_addr");
  if (SA > 0 && ams != nullptr)
    snap->attr_member_slot.assign(ams, ams + SA);
  if (SA > 0 && abs_v != nullptr)
    snap->attr_byte_slot_v.assign(abs_v, abs_v + SA);
  snap->attr_member_slot.resize(SA, -1);
  snap->attr_byte_slot_v.resize(SA, -1);
  // dfa_R counts TOTAL stacked rows (S*R for sharded corpora); attr_dfas
  // rows arrive globalized by the Python side
  long dfa_R = dict_int(d, "dfa_R");
  snap->dfa_S = (int)dict_int(d, "dfa_S");
  if (dfa_R > 0 && snap->dfa_S > 0) {
    const uint8_t* tr = (const uint8_t*)dict_addr(d, "dfa_trans_addr");
    const uint8_t* ac = (const uint8_t*)dict_addr(d, "dfa_accept_addr");
    snap->dfa_trans.assign(tr, tr + (size_t)dfa_R * snap->dfa_S * 256);
    snap->dfa_accept.assign(ac, ac + (size_t)dfa_R * snap->dfa_S);
  }
  snap->attr_dfas.resize(SA);
  PyObject* adfas = PyDict_GetItemString(d, "attr_dfas");
  if (adfas != nullptr) {
    for (Py_ssize_t a = 0; a < PyList_GET_SIZE(adfas) && a < SA; ++a) {
      PyObject* lst = PyList_GET_ITEM(adfas, a);
      for (Py_ssize_t j = 0; j < PyList_GET_SIZE(lst); ++j) {
        PyObject* t = PyList_GET_ITEM(lst, j);
        snap->attr_dfas[a].push_back(
            {(int32_t)PyLong_AsLong(PyTuple_GET_ITEM(t, 0)),
             (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(t, 1))});
      }
    }
  }
  if (!dict_bytes(d, "invalid", snap->invalid_msg) ||
      !dict_bytes(d, "notfound", snap->notfound_msg) ||
      !dict_bytes(d, "health", snap->health_msg)) {
    PyErr_SetString(PyExc_ValueError, "swap spec missing response templates");
    return nullptr;
  }
  PyObject* fcs = PyDict_GetItemString(d, "fcs");
  for (Py_ssize_t i = 0; fcs != nullptr && i < PyList_GET_SIZE(fcs); ++i) {
    PyObject* f = PyList_GET_ITEM(fcs, i);
    fe::FastConfig fc;
    fc.row = (int32_t)dict_int(f, "row");
    fc.shard = (int32_t)dict_int(f, "shard", 0);
    fc.has_batch = dict_int(f, "has_batch", 1) != 0;
    fc.hybrid = dict_int(f, "hybrid", 0) != 0;
    dict_bytes(f, "ok", fc.ok_msg);
    dict_bytes(f, "deny", fc.deny_msg);
    if (!parse_plans(PyDict_GetItemString(f, "plans"), fc.plans, &fc.needs_split))
      return nullptr;
    dict_str(f, "ns", fc.ns);
    dict_str(f, "name", fc.name);
    PyObject* srcs = PyDict_GetItemString(f, "sources");
    for (Py_ssize_t j = 0; srcs != nullptr && j < PyList_GET_SIZE(srcs); ++j) {
      PyObject* sd = PyList_GET_ITEM(srcs, j);
      fe::CredSource src;
      src.cred_kind = (int)dict_int(sd, "cred_kind", 0);
      src.dyn = dict_int(sd, "dyn", 0) != 0;
      dict_str(sd, "cred_key", src.cred_key);
      PyObject* vars = PyDict_GetItemString(sd, "variants");
      for (Py_ssize_t k = 0; vars != nullptr && k < PyList_GET_SIZE(vars); ++k) {
        // (key_bytes, plans, ok_bytes, deny_bytes) — empty = config default
        PyObject* kv = PyList_GET_ITEM(vars, k);
        PyObject* kb = PyTuple_GET_ITEM(kv, 0);
        PyObject* okb = PyTuple_GET_SIZE(kv) > 2 ? PyTuple_GET_ITEM(kv, 2) : nullptr;
        PyObject* dnb = PyTuple_GET_SIZE(kv) > 3 ? PyTuple_GET_ITEM(kv, 3) : nullptr;
        if (!PyBytes_Check(kb) || (okb != nullptr && !PyBytes_Check(okb)) ||
            (dnb != nullptr && !PyBytes_Check(dnb))) {
          PyErr_SetString(PyExc_TypeError, "variant key/ok/deny must be bytes");
          return nullptr;
        }
        std::vector<fe::FastPlan> vp;
        if (!parse_plans(PyTuple_GET_ITEM(kv, 1), vp, nullptr)) return nullptr;
        int32_t vid = (int32_t)src.var_plans.size();
        src.var_plans.push_back(std::move(vp));
        int32_t ok_idx = -1;
        if (okb != nullptr && PyBytes_GET_SIZE(okb) > 0) {
          ok_idx = (int32_t)src.var_oks.size();
          src.var_oks.emplace_back(PyBytes_AS_STRING(okb),
                                   (size_t)PyBytes_GET_SIZE(okb));
        }
        int32_t deny_idx = -1;
        if (dnb != nullptr && PyBytes_GET_SIZE(dnb) > 0) {
          deny_idx = (int32_t)src.var_denies.size();
          src.var_denies.emplace_back(PyBytes_AS_STRING(dnb),
                                      (size_t)PyBytes_GET_SIZE(dnb));
        }
        src.variants[std::string(PyBytes_AS_STRING(kb),
                                 (size_t)PyBytes_GET_SIZE(kb))] = {
            vid, INT64_MAX, ok_idx, deny_idx};
      }
      fc.sources.push_back(std::move(src));
    }
    PyObject* umsgs = PyDict_GetItemString(f, "unauth_msgs");
    for (Py_ssize_t j = 0; umsgs != nullptr && j < PyList_GET_SIZE(umsgs); ++j) {
      PyObject* b = PyList_GET_ITEM(umsgs, j);
      if (!PyBytes_Check(b)) {
        PyErr_SetString(PyExc_TypeError, "unauth template must be bytes");
        return nullptr;
      }
      fc.unauth_msgs.emplace_back(PyBytes_AS_STRING(b),
                                  (size_t)PyBytes_GET_SIZE(b));
    }
    if (!fc.sources.empty()) {
      size_t n_static = 0;
      for (const auto& s : fc.sources) n_static += s.dyn ? 0 : 1;
      if (fc.unauth_msgs.size() != ((size_t)1 << n_static)) {
        PyErr_SetString(PyExc_ValueError,
                        "unauth_msgs must cover every static-extraction mask");
        return nullptr;
      }
    }
    snap->fcs.push_back(std::move(fc));
  }
  snap->fc_counts.reset(new std::atomic<uint64_t>[snap->fcs.size() * 3 + 1]());
  snap->fc_durs.reset(
      new std::atomic<uint64_t>[snap->fcs.size() * fe::DUR_STRIDE + 1]());
  PyObject* hosts = PyDict_GetItemString(d, "hosts");
  for (Py_ssize_t i = 0; hosts != nullptr && i < PyList_GET_SIZE(hosts); ++i) {
    PyObject* t = PyList_GET_ITEM(hosts, i);
    Py_ssize_t hn;
    const char* hs = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(t, 0), &hn);
    if (hs == nullptr) return nullptr;
    snap->host_map[std::string(hs, (size_t)hn)] =
        (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(t, 1));
  }
  PyObject* slots = PyDict_GetItemString(d, "slots");
  for (Py_ssize_t i = 0; slots != nullptr && i < PyList_GET_SIZE(slots); ++i) {
    PyObject* s = PyList_GET_ITEM(slots, i);
    fe::Slot sl;
    sl.attrs_val = (char*)dict_addr(s, "attrs_val");
    sl.members = (char*)dict_addr(s, "members");
    sl.cpu_dense = (uint8_t*)dict_addr(s, "cpu_dense");
    sl.config_id = (int32_t*)dict_addr(s, "config_id");
    sl.shard_of = (int32_t*)dict_addr(s, "shard_of");
    sl.attr_bytes = (uint8_t*)dict_addr(s, "attr_bytes");
    sl.byte_ovf = (uint8_t*)dict_addr(s, "byte_ovf");
    snap->slots.push_back(sl);
    snap->free_slots.push_back((int)i);
  }
  snap->slot_entries.resize(snap->slots.size());
  snap->slot_count.resize(snap->slots.size(), 0);
  snap->slot_flush_ns.resize(snap->slots.size(), 0);

  std::vector<int64_t> retired;
  {
    std::lock_guard<std::mutex> lk(S->mu);
    S->snaps[snap->id] = snap;
    S->cur = snap;
    fe::maybe_retire_locked(S, retired);
  }
  fe::emit_retired(S, retired);
  return PyLong_FromLong(0);
}

// fe_wait_batch(timeout_ms) -> (kind, a, b, c)
PyObject* fe_wait_batch_py(PyObject*, PyObject* args) {
  long timeout_ms;
  if (!PyArg_ParseTuple(args, "l", &timeout_ms)) return nullptr;
  fe::Server* S = fe::g_srv;
  if (S == nullptr) return Py_BuildValue("(iLLL)", (int)fe::EV_STOPPED, 0LL, 0LL, 0LL);
  fe::Event ev = {fe::EV_TIMEOUT, 0, 0, 0};
  Py_BEGIN_ALLOW_THREADS {
    std::unique_lock<std::mutex> lk(S->batch_mu);
    if (S->batch_events.empty())
      S->batch_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                           [&] { return !S->batch_events.empty(); });
    if (!S->batch_events.empty()) {
      ev = S->batch_events.front();
      S->batch_events.pop_front();
    }
  }
  Py_END_ALLOW_THREADS
  return Py_BuildValue("(iLLL)", ev.kind, (long long)ev.a, (long long)ev.b,
                       (long long)ev.c);
}

// fe_take_slow(timeout_ms, max_n) -> list[(req_id, bytes)]
PyObject* fe_take_slow_py(PyObject*, PyObject* args) {
  long timeout_ms;
  int max_n;
  if (!PyArg_ParseTuple(args, "li", &timeout_ms, &max_n)) return nullptr;
  fe::Server* S = fe::g_srv;
  if (S == nullptr) return PyList_New(0);
  std::vector<fe::SlowReq> reqs;
  Py_BEGIN_ALLOW_THREADS {
    std::unique_lock<std::mutex> lk(S->slow_mu);
    if (S->slow_q.empty())
      S->slow_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                          [&] { return !S->slow_q.empty() || !S->running.load(); });
    while (!S->slow_q.empty() && (int)reqs.size() < max_n) {
      reqs.push_back(std::move(S->slow_q.front()));
      S->slow_q.pop_front();
    }
  }
  Py_END_ALLOW_THREADS
  PyObject* out = PyList_New((Py_ssize_t)reqs.size());
  for (size_t i = 0; i < reqs.size(); ++i) {
    PyObject* b = PyBytes_FromStringAndSize(reqs[i].bytes.data(),
                                            (Py_ssize_t)reqs[i].bytes.size());
    PyList_SET_ITEM(out, (Py_ssize_t)i,
                    Py_BuildValue("(KN)", (unsigned long long)reqs[i].id, b));
  }
  return out;
}

// fe_complete_batch(snap_id, slot, verdict_addr)
PyObject* fe_complete_batch_py(PyObject*, PyObject* args) {
  long long snap_id;
  int slot;
  unsigned long long verdict_a;
  if (!PyArg_ParseTuple(args, "LiK", &snap_id, &slot, &verdict_a)) return nullptr;
  fe::Server* S = fe::g_srv;
  if (S != nullptr) {
    Py_BEGIN_ALLOW_THREADS
    fe::complete_batch(S, snap_id, slot, (const uint8_t*)verdict_a);
    Py_END_ALLOW_THREADS
  }
  Py_RETURN_NONE;
}

// fe_complete_slow(req_id, resp_bytes, grpc_status)
PyObject* fe_complete_slow_py(PyObject*, PyObject* args) {
  unsigned long long req_id;
  Py_buffer resp;
  int grpc_status;
  if (!PyArg_ParseTuple(args, "Ky*i", &req_id, &resp, &grpc_status)) return nullptr;
  fe::Server* S = fe::g_srv;
  if (S != nullptr) {
    // complete_slow contends on the server mutex with the epoll thread —
    // release the GIL so that wait never blocks the Python slow lane
    Py_BEGIN_ALLOW_THREADS
    fe::complete_slow(S, req_id, (const char*)resp.buf, (size_t)resp.len, grpc_status);
    Py_END_ALLOW_THREADS
  }
  PyBuffer_Release(&resp);
  Py_RETURN_NONE;
}

// fe_complete_slow_many([(req_id, resp_bytes, grpc_status), ...]) — batch
// completion: copies the payloads under the GIL, lands them all in two
// lock rounds with the GIL released
PyObject* fe_complete_slow_many_py(PyObject*, PyObject* args) {
  PyObject* lst;
  if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &lst)) return nullptr;
  std::vector<fe::SlowDone> items;
  items.reserve((size_t)PyList_GET_SIZE(lst));
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(lst); ++i) {
    PyObject* t = PyList_GET_ITEM(lst, i);
    unsigned long long req_id;
    Py_buffer resp;
    int grpc_status;
    if (!PyArg_ParseTuple(t, "Ky*i", &req_id, &resp, &grpc_status))
      return nullptr;
    items.push_back({req_id, std::string((const char*)resp.buf,
                                         (size_t)resp.len), grpc_status});
    PyBuffer_Release(&resp);
  }
  fe::Server* S = fe::g_srv;
  if (S != nullptr && !items.empty()) {
    Py_BEGIN_ALLOW_THREADS
    fe::complete_slow_many(S, items);
    Py_END_ALLOW_THREADS
  }
  Py_RETURN_NONE;
}

// fe_add_variant(snap_id, fc_idx, src_idx, cred_bytes, plans, ok_bytes,
// deny_bytes, exp_ns) -> bool — register a runtime plan variant
// (verified-credential cache entry) for one identity source; called by the
// slow lane after a successful verification.  Empty ok/deny bytes = the
// config's defaults.
PyObject* fe_add_variant_py(PyObject*, PyObject* args) {
  long long snap_id, exp_ns;
  int fc_idx, src_idx;
  Py_buffer cred, okb, dnb;
  PyObject* plans;
  if (!PyArg_ParseTuple(args, "Liiy*O!y*y*L", &snap_id, &fc_idx, &src_idx,
                        &cred, &PyList_Type, &plans, &okb, &dnb, &exp_ns))
    return nullptr;
  fe::Server* S = fe::g_srv;
  std::vector<fe::FastPlan> vp;
  bool parsed = S != nullptr && parse_plans(plans, vp, nullptr);
  std::string cs((const char*)cred.buf, (size_t)cred.len);
  std::string oks((const char*)okb.buf, (size_t)okb.len);
  std::string dns((const char*)dnb.buf, (size_t)dnb.len);
  PyBuffer_Release(&cred);
  PyBuffer_Release(&okb);
  PyBuffer_Release(&dnb);
  if (S == nullptr) Py_RETURN_FALSE;
  if (!parsed) return nullptr;
  bool ok;
  Py_BEGIN_ALLOW_THREADS
  ok = fe::add_variant(S, snap_id, fc_idx, src_idx, std::move(cs),
                       std::move(vp), std::move(oks), std::move(dns), exp_ns);
  Py_END_ALLOW_THREADS
  return PyBool_FromLong(ok ? 1 : 0);
}

// fe_drain_fc_counts() -> list[(ns, name, ok, unauth_missing, unauth_invalid)]
// — per-authconfig direct decisions since the last drain (the dispatcher
// folds them into the pipeline's Prometheus series)
PyObject* fe_drain_fc_counts_py(PyObject*, PyObject*) {
  fe::Server* S = fe::g_srv;
  PyObject* out = PyList_New(0);
  if (S == nullptr || out == nullptr) return out;
  std::unordered_map<std::string, std::array<uint64_t, 3>> agg;
  Py_BEGIN_ALLOW_THREADS
  fe::drain_fc_counts(S, agg);
  Py_END_ALLOW_THREADS
  for (auto& kv : agg) {
    size_t sep = kv.first.find('\x1f');
    if (sep == std::string::npos) continue;
    PyObject* t = Py_BuildValue(
        "(s#s#KKK)", kv.first.data(), (Py_ssize_t)sep, kv.first.data() + sep + 1,
        (Py_ssize_t)(kv.first.size() - sep - 1),
        (unsigned long long)kv.second[0], (unsigned long long)kv.second[1],
        (unsigned long long)kv.second[2]);
    if (t == nullptr) { Py_DECREF(out); return nullptr; }
    PyList_Append(out, t);
    Py_DECREF(t);
  }
  return out;
}

// fe_drain_durations() -> list[(ns, name, [15 bucket counts], sum_ns)] —
// per-authconfig request-duration histogram increments since the last
// drain; the dispatcher folds them into
// auth_server_authconfig_duration_seconds (same buckets as prometheus
// defaults, non-cumulative per-le counts)
PyObject* fe_drain_durations_py(PyObject*, PyObject*) {
  fe::Server* S = fe::g_srv;
  PyObject* out = PyList_New(0);
  if (S == nullptr || out == nullptr) return out;
  std::unordered_map<std::string, std::array<uint64_t, fe::DUR_STRIDE>> agg;
  Py_BEGIN_ALLOW_THREADS
  fe::drain_durations(S, agg);
  Py_END_ALLOW_THREADS
  for (auto& kv : agg) {
    size_t sep = kv.first.find('\x1f');
    if (sep == std::string::npos) continue;
    PyObject* buckets = PyList_New(fe::N_DUR_BUCKETS);
    if (buckets == nullptr) { Py_DECREF(out); return nullptr; }
    for (int k = 0; k < fe::N_DUR_BUCKETS; ++k)
      PyList_SET_ITEM(buckets, k, PyLong_FromUnsignedLongLong(kv.second[k]));
    PyObject* t = Py_BuildValue(
        "(s#s#NK)", kv.first.data(), (Py_ssize_t)sep, kv.first.data() + sep + 1,
        (Py_ssize_t)(kv.first.size() - sep - 1), buckets,
        (unsigned long long)kv.second[fe::N_DUR_BUCKETS]);
    if (t == nullptr) { Py_DECREF(out); return nullptr; }
    PyList_Append(out, t);
    Py_DECREF(t);
  }
  return out;
}

// fe_stage_hist() -> {"wait": [...], "exec": [...], "respond": [...],
// "bounds_ns": [...]} — drains (resets) the on-box per-request stage
// histograms: queue-wait (encode→flush), execute (flush→complete),
// respond (complete→HTTP/2 submit)
PyObject* fe_stage_hist_py(PyObject*, PyObject*) {
  fe::Server* S = fe::g_srv;
  PyObject* d = PyDict_New();
  if (S == nullptr || d == nullptr) return d;
  auto dump = [&](const char* key, std::atomic<uint64_t>* arr) {
    PyObject* l = PyList_New(fe::N_STAGE_BUCKETS);
    for (int i = 0; i < fe::N_STAGE_BUCKETS; ++i)
      PyList_SET_ITEM(l, i, PyLong_FromUnsignedLongLong(arr[i].exchange(0)));
    PyDict_SetItemString(d, key, l);
    Py_DECREF(l);
  };
  dump("wait", S->stage_wait);
  dump("exec", S->stage_exec);
  dump("respond", S->stage_respond);
  PyObject* b = PyList_New(fe::N_STAGE_BUCKETS - 1);
  for (int i = 0; i < fe::N_STAGE_BUCKETS - 1; ++i)
    PyList_SET_ITEM(b, i, PyLong_FromLongLong(fe::STAGE_BOUNDS_NS[i]));
  PyDict_SetItemString(d, "bounds_ns", b);
  Py_DECREF(b);
  return d;
}

PyObject* fe_stats_py(PyObject*, PyObject*) {
  fe::Server* S = fe::g_srv;
  PyObject* d = PyDict_New();
  if (S == nullptr) return d;
  auto put = [&](const char* k, uint64_t v) {
    PyObject* o = PyLong_FromUnsignedLongLong(v);
    PyDict_SetItemString(d, k, o);
    Py_DECREF(o);
  };
  put("fast", S->n_fast.load());
  put("slow", S->n_slow.load());
  put("notfound", S->n_notfound.load());
  put("invalid", S->n_invalid.load());
  put("health", S->n_health.load());
  put("allowed", S->n_allowed.load());
  put("denied", S->n_denied.load());
  put("dfa_overflow", S->n_dfa_ovf.load());
  put("slow_shed", S->n_slow_shed.load());
  put("parse_errors", S->n_parse_err.load());
  put("connections", S->n_conns.load());
  put("unauth", S->n_unauth.load());
  put("direct_ok", S->n_direct_ok.load());
  put("dyn_hit", S->n_dyn_hit.load());
  put("dyn_miss", S->n_dyn_miss.load());
  put("dyn_add", S->n_dyn_add.load());
  put("trace_sampled", S->n_trace_sampled.load());
  put("hybrid", S->n_hybrid.load());
  {
    // live backlog gauges (not counters): queued + in-pipeline slow work
    size_t pending, queued;
    {
      std::lock_guard<std::mutex> lk(S->mu);
      pending = S->slow_pending.size();
    }
    {
      std::lock_guard<std::mutex> lk(S->slow_mu);
      queued = S->slow_q.size();
    }
    put("slow_pending", pending);
    put("slow_queued", queued);
  }
  return d;
}

PyMethodDef methods[] = {
    {"policy_new", policy_new_py, METH_VARARGS, "build native policy tables"},
    {"encode_docs", encode_docs, METH_VARARGS, "encode a batch of dict docs"},
    {"encode_json", encode_json_py, METH_VARARGS, "encode a JSON-blob batch"},
    {"fe_start", fe_start_py, METH_VARARGS, "start the native gRPC frontend"},
    {"fe_stop", fe_stop_py, METH_NOARGS, "stop the native gRPC frontend"},
    {"fe_port", fe_port_py, METH_NOARGS, "bound port of the frontend"},
    {"fe_swap", fe_swap_py, METH_VARARGS, "swap the frontend snapshot"},
    {"fe_wait_batch", fe_wait_batch_py, METH_VARARGS, "wait for a batch event"},
    {"fe_take_slow", fe_take_slow_py, METH_VARARGS, "take queued slow-lane requests"},
    {"fe_complete_batch", fe_complete_batch_py, METH_VARARGS, "complete a batch"},
    {"fe_complete_slow", fe_complete_slow_py, METH_VARARGS, "complete a slow request"},
    {"fe_complete_slow_many", fe_complete_slow_many_py, METH_VARARGS,
     "complete a batch of slow requests"},
    {"fe_add_variant", fe_add_variant_py, METH_VARARGS,
     "register a runtime credential plan variant"},
    {"fe_stats", fe_stats_py, METH_NOARGS, "frontend counters"},
    {"fe_drain_fc_counts", fe_drain_fc_counts_py, METH_NOARGS,
     "drain per-authconfig direct-decision counters"},
    {"fe_drain_durations", fe_drain_durations_py, METH_NOARGS,
     "drain per-authconfig duration histograms"},
    {"fe_stage_hist", fe_stage_hist_py, METH_NOARGS,
     "drain the on-box per-request stage histograms"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "_atpuenc",
                      "native batch encoder", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__atpuenc(void) {
  PyObject* json_mod = PyImport_ImportModule("json");
  if (json_mod == nullptr) return nullptr;
  g_json_dumps = PyObject_GetAttrString(json_mod, "dumps");
  Py_DECREF(json_mod);
  if (g_json_dumps == nullptr) return nullptr;
  g_dumps_kwargs = Py_BuildValue("{s:(s,s),s:O}", "separators", ",", ":",
                                 "ensure_ascii", Py_False);
  if (g_dumps_kwargs == nullptr) return nullptr;
  return PyModule_Create(&module);
}
